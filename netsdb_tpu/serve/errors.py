"""Typed failure taxonomy for the serve control plane.

The reference surfaces every RPC failure as an ``errMsg`` string the
caller string-matches (``src/communication/headers/PDBCommunicator.h``);
we instead split faults into two machine-readable families so the
client can decide mechanically:

* **retryable** — the request may not have been observed, or the
  condition is transient: connection reset, mid-frame truncation,
  corrupt frame, admission queue full, follower degraded/resyncing.
  :class:`RemoteClient` retries these with exponential backoff +
  jitter, bounded by a per-request deadline. Mutating frames carry an
  idempotency token so a retry after an ambiguous outcome (the server
  may have applied the mutation but the reply was lost) is deduplicated
  server-side instead of double-applied.
* **fatal** — the request was observed and deterministically refused:
  handler errors, protocol violations, refused codecs, bad auth.
  Retrying would yield the same answer; the error is raised immediately.

Server side, handlers raise :class:`ServeFault` subclasses whose
``retryable`` flag crosses the wire in the ERR payload; client side,
:func:`classify_remote` rebuilds the matching :class:`RemoteError`
subclass from the frame. Both halves live in one module so the kind
names cannot drift apart.
"""

from __future__ import annotations

from typing import Any, Dict


# --- server-side faults ------------------------------------------------

class ServeFault(Exception):
    """A fault a server handler raises deliberately. ``retryable``
    rides the ERR payload so clients classify without string-matching;
    ``kind`` is the wire name (defaults to the class name)."""

    retryable = False

    @property
    def kind(self) -> str:
        return type(self).__name__


class AdmissionFull(ServeFault):
    """The job-admission layer did not free a slot within the
    admission timeout — back off and retry (the reference's
    QuerySchedulerServer would park the job; we refuse typed instead of
    wedging a handler thread). ``retry_after_s`` is the scheduler's
    OWN backoff hint — the lane's observed queue-wait median, which a
    client honors instead of blind exponential jitter; ``queue_depth``
    and ``lane`` identify how deep behind which lane the request was
    parked. All three ride the ERR payload."""

    retryable = True

    def __init__(self, *args, retry_after_s=None, queue_depth=None,
                 lane=None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        self.lane = lane


class LaneSaturated(ServeFault):
    """One client lane's admission QUOTA is full — distinct from
    :class:`AdmissionFull` (the whole daemon saturated) by design: the
    right client reaction is per-tenant backoff, not failover, and an
    operator alerting on quota rejections must be able to tell "this
    tenant is over its share" from "the daemon is drowning". Carries
    the lane's observed queue depth and the scheduler's
    ``retry_after_s`` hint (the lane's queue-wait median)."""

    retryable = True

    def __init__(self, *args, lane=None, queue_depth=None,
                 retry_after_s=None):
        super().__init__(*args)
        self.lane = lane
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


class CoalesceAborted(ServeFault):
    """A coalesced waiter's leader execution died (or outlived the
    coalesce wait bound) before producing a reply. The waiter's own
    request never ran and nothing was applied under its token — a
    retry re-executes from scratch: a FAILED leader's flight leaves
    the table before waiters release, and an over-age (still-running)
    flight is never re-joined, so the retry runs solo. Never carries
    a partial reply: a waiter gets the leader's COMPLETE result or
    this typed retryable error."""

    retryable = True


class FollowerDegraded(ServeFault):
    """A follower failed mid-mirror (or a resync is in progress). The
    leader keeps serving from its own store; the follower is evicted
    and resynced in the background. When the local mutation already
    applied, ``local_result`` carries its reply so the idempotent retry
    returns success without re-executing."""

    retryable = True

    def __init__(self, *args):
        super().__init__(*args)
        self.local_result = None


class CorruptFrame(ServeFault):
    """A frame arrived but its body failed to decode (bit flips, torn
    writes). The request was never executed, so a resend is safe."""

    retryable = True


class PlacementStale(ServeFault):
    """A frame routed under an out-of-date placement map: its epoch no
    longer matches the target set's (the leader evicted or readmitted
    a shard since the sender's map was fetched), or the sender didn't
    know the set was partitioned at all. Nothing was applied — the
    typed retryable contract is refresh-then-re-route: the client
    re-fetches the map (``RemoteClient`` does this automatically
    between attempts) and re-partitions against current membership.
    ``epoch`` carries the receiver's current epoch for the set."""

    retryable = True

    def __init__(self, *args, epoch=None):
        super().__init__(*args)
        self.epoch = epoch


class ShardUnavailable(ServeFault):
    """A scatter-gather coordinator (or routed ingest) needs a shard
    slot that is currently degraded/unreachable. The query was NOT
    partially merged — partials are discarded whole, never combined
    across epochs — and retrying after the shard readmits (or the
    leader revises placement) succeeds. Carries the affected ``slot``
    and the set's current ``epoch``."""

    retryable = True

    def __init__(self, *args, slot=None, epoch=None):
        super().__init__(*args)
        self.slot = slot
        self.epoch = epoch


class NotLeader(ServeFault):
    """This daemon cannot accept the write: it is an HA follower (the
    client aimed at the wrong daemon, or a failover moved the role),
    or the frame carried a STALE term (a deposed leader's straggler —
    fenced, never applied). ``leader_addr`` carries the leader this
    daemon knows about (None mid-election) so the client re-points
    WITHOUT a discovery scan; ``term`` is this daemon's current term.
    Retryable by contract: nothing was applied, and the retry against
    the right leader dedupes under the same idempotency token."""

    retryable = True

    def __init__(self, *args, leader_addr=None, term=None):
        super().__init__(*args)
        self.leader_addr = leader_addr
        self.term = term


class SessionMoved(ServeFault):
    """A session-scoped frame (GENERATE / SESSION_CLOSE) arrived at a
    daemon that no longer owns the session's state: the session was
    relocated (owner death adoption, a live session rebalance) or the
    frame hit the leader for a worker-owned session. Nothing was
    applied — the state advanced zero steps here. ``owner_addr`` names
    the daemon that owns it NOW (None when only a table lookup at the
    leader can answer), so the client's sticky handle re-points
    without a discovery scan and retries under the same idempotency
    token."""

    retryable = True

    def __init__(self, *args, owner_addr=None):
        super().__init__(*args)
        self.owner_addr = owner_addr


class SessionUnknown(ServeFault):
    """The session id is not in the (replicated) session table: never
    opened here, already closed, or expired past its TTL with no spill
    left to revive from. Fatal by contract — retrying the same handle
    cannot help; the caller opens a fresh session."""

    retryable = False


class RequestInFlight(ServeFault):
    """A duplicate idempotency token arrived while the original request
    is still executing; the retry should back off and re-ask (it will
    then hit the completed-result cache)."""

    retryable = True


# --- client-side errors ------------------------------------------------

class RemoteError(RuntimeError):
    """Base: a request failed. ``kind`` is the server-side exception
    class name (or the local failure type), ``remote_traceback`` the
    server traceback when one crossed the wire. Fatal unless a subclass
    says otherwise."""

    retryable = False

    def __init__(self, kind: str, message: str, remote_traceback: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.remote_traceback = remote_traceback
        # scheduler backpressure details (populated by classify_remote
        # when the ERR frame carried them — AdmissionFull/LaneSaturated)
        self.retry_after_s = None
        self.queue_depth = None
        self.lane = None
        # placement details (PlacementStale/ShardUnavailable family)
        self.epoch = None
        self.slot = None
        # HA failover details (NotLeader family): where the leader
        # moved and the rejecting daemon's term
        self.leader_addr = None
        self.term = None
        # session stickiness details (SessionMoved family): where the
        # session's state lives now
        self.owner_addr = None


class RetryableRemoteError(RemoteError):
    """The transient family — safe to resend (mutations are deduped
    server-side via the idempotency token)."""

    retryable = True


class ConnectionLostError(RetryableRemoteError):
    """The transport died mid-request (reset, refused dial, peer closed
    mid-frame). The outcome is ambiguous: the server may or may not
    have executed the request — exactly what idempotency tokens are
    for."""


class RemoteTimeoutError(RetryableRemoteError):
    """The socket-level timeout expired waiting for the peer."""


class AdmissionFullError(RetryableRemoteError):
    """Server-side :class:`AdmissionFull` — job queue saturated. When
    the frame carried one, ``retry_after_s`` is the scheduler's
    backoff hint (the lane's observed queue-wait median) and the
    client's retry loop sleeps THAT instead of blind exponential
    jitter."""


class LaneSaturatedError(RetryableRemoteError):
    """Server-side :class:`LaneSaturated` — THIS client's lane quota
    is full (the daemon may be otherwise idle). ``lane``,
    ``queue_depth`` and ``retry_after_s`` carry the scheduler's view;
    back off per-tenant, don't fail over."""


class CoalesceAbortedError(RetryableRemoteError):
    """Server-side :class:`CoalesceAborted` — this request was
    coalesced behind an identical in-flight execution whose leader
    died mid-run. Nothing executed under this request; a retry
    re-executes from scratch."""


class FollowerDegradedError(RetryableRemoteError):
    """Server-side :class:`FollowerDegraded` — a follower was evicted
    mid-request or a resync holds the mutation path. The leader applied
    the local mutation; the idempotent retry returns its result."""


class CorruptFrameError(RetryableRemoteError):
    """Server-side :class:`CorruptFrame` — the frame body failed to
    decode; the request never ran."""


class PlacementStaleError(RetryableRemoteError):
    """Server-side :class:`PlacementStale` — the frame rode an
    out-of-date placement map and was rejected whole. ``epoch`` (when
    the frame carried it) is the receiver's current epoch for the set;
    :class:`RemoteClient` refreshes its cached map between attempts so
    the retry re-routes against current membership."""


class ShardUnavailableError(RetryableRemoteError):
    """Server-side :class:`ShardUnavailable` — a shard slot the
    request needs is degraded. Nothing was partially applied or
    merged; retry after the pool heals (backoff applies)."""


class NotLeaderError(RetryableRemoteError):
    """Server-side :class:`NotLeader` — the daemon is a follower (or a
    deposed leader that already fenced this client's frame).
    ``leader_addr`` (when the rejection carried one) names the daemon
    to re-point at; :class:`RemoteClient` switches its address and
    retries immediately, or backs off through the election window when
    no leader is known yet. ``term`` is the rejecting daemon's current
    term."""


class SessionMovedError(RetryableRemoteError):
    """Server-side :class:`SessionMoved` — the session's state lives on
    a different daemon now. ``owner_addr`` (when the rejection carried
    one) names the new owner; the client's session handle re-points at
    it — or re-asks the leader's session table when it didn't — and
    retries under the same token. The typed relocation signal that
    makes stickiness survive rebalance and failover."""


class SessionUnknownError(RemoteError):
    """Server-side :class:`SessionUnknown` — the session id is gone
    (closed or TTL-expired with no spill). Fatal: open a new
    session."""


class AuthError(RemoteError):
    """Handshake refused — fatal, retrying cannot help."""


class ProtocolVersionError(RemoteError):
    """The peer speaks a different wire-format version (HELLO carries
    ``proto``; see ``protocol.PROTO_VERSION``). Fatal by construction:
    a v2 peer would misparse a v3 out-of-band segment table as body
    bytes, so mixed-version connections are refused at handshake."""


class DeadlineExceededError(RemoteError):
    """The per-request deadline expired before a retry could succeed.
    Deliberately NOT retryable: the budget is spent; the caller decides
    whether to re-issue with a fresh deadline."""


_KIND_MAP: Dict[str, type] = {
    "AdmissionFull": AdmissionFullError,
    "LaneSaturated": LaneSaturatedError,
    "CoalesceAborted": CoalesceAbortedError,
    "FollowerDegraded": FollowerDegradedError,
    "CorruptFrame": CorruptFrameError,
    "PlacementStale": PlacementStaleError,
    "ShardUnavailable": ShardUnavailableError,
    "NotLeader": NotLeaderError,
    "SessionMoved": SessionMovedError,
    "SessionUnknown": SessionUnknownError,
    "AuthError": AuthError,
    "ProtocolVersionError": ProtocolVersionError,
}

#: scheduler-backpressure detail fields that cross the wire inside the
#: ERR payload (server ``_send_err`` includes them when the fault
#: carries them; ``classify_remote`` rebuilds them on the error).
#: ``epoch``/``slot`` are the placement family's analogues: the
#: receiver's current epoch rides the rejection so a client can tell
#: "my map is stale" from "the pool is degraded".
#: ``leader_addr``/``term`` are the HA family's: a NotLeader rejection
#: names the daemon to re-point at and the rejecting daemon's term.
#: ``owner_addr`` is the session family's: a SessionMoved rejection
#: names the daemon holding the session's state now.
BACKPRESSURE_FIELDS = ("retry_after_s", "queue_depth", "lane",
                       "epoch", "slot", "leader_addr", "term",
                       "owner_addr")


def classify_remote(reply: Dict[str, Any]) -> RemoteError:
    """ERR frame payload → the matching typed error. Known kinds map to
    their dedicated class; unknown kinds fall back on the frame's
    ``retryable`` flag (so new server faults degrade gracefully to the
    right *family* on old clients). Scheduler backpressure details
    (``retry_after_s``/``queue_depth``/``lane``) are rebuilt onto the
    error so the retry loop can honor the server's hint."""
    kind = reply.get("error", "Error")
    message = reply.get("message", "")
    tb = reply.get("traceback", "")
    cls = _KIND_MAP.get(kind)
    if cls is None:
        cls = RetryableRemoteError if reply.get("retryable") else RemoteError
    err = cls(kind, message, tb)
    for field in BACKPRESSURE_FIELDS:
        if reply.get(field) is not None:
            setattr(err, field, reply[field])
    return err
