"""AST lint framework — typed, pluggable static rules over the tree.

PR 6 shipped a real lock inversion (``store.add_data`` held the global
store lock across ``PagedObjects.append``) that a human reviewer
caught, not tooling: the old ``tests/test_static_checks.py`` scanners
were per-file AST walks that could not see lock *nesting*, aliases, or
resource lifetimes.  This package is the replacement — one framework,
many small typed rules, one entry point (``python -m netsdb_tpu.cli
lint``) shared by CI and humans.

Design:

* **Parse once.** Every target file becomes a :class:`Module` (source,
  AST, suppression table) built exactly once and shared by all rules —
  the whole-tree run stays well under the 10 s CI budget.
* **Two rule scopes.** A rule may implement :meth:`Rule.check_module`
  (per-file diagnostics) and/or :meth:`Rule.check_project`
  (whole-tree passes — the lock-order graph, the metric-catalog drift
  check — anything that must see every module at once).
* **Typed diagnostics.** Every finding is a :class:`Diagnostic`
  (rule id, repo-relative path, line, column, message) — renderable
  as ``file:line:col: [rule-id] message`` or JSON.
* **Per-rule suppression comments.** ``# lint: disable=<rule-id>[,
  <rule-id>] -- <reason>`` on the flagged line (or the line directly
  above it) suppresses matching diagnostics.  The reason is
  MANDATORY: a suppression without one is itself a diagnostic
  (``bad-suppression``), and a suppression that never fires on a
  full-rule-set run is flagged too (``unused-suppression``) so stale
  exemptions cannot accumulate.  Rule catalogs live in
  ``docs/ANALYSIS.md``; the ``analysis-docs-drift`` rule keeps that
  file and the registered rule set agreeing in both directions.

The framework itself stays stdlib-only (ast/os/re/json): ``cli lint``
must run without importing jax.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

#: repo root (the directory holding netsdb_tpu/ and tests/)
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: the default lint target — the whole package tree
PKG_DIR = os.path.join(REPO, "netsdb_tpu")

#: suppression comment grammar: ``lint: disable=<rule>[,<rule>] --
#: <reason>`` as a comment on the flagged line or the line above
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$")

#: framework-level diagnostic ids (reserved; not Rule subclasses)
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"
PARSE_ERROR = "parse-error"
STALE_BASELINE = "stale-baseline"


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: where, which rule, what — plus, for findings with
    a mechanical-but-human-applied remedy, a rendered ``suggestion``
    diff (the iter-close assigned-never-closed shape)."""

    rule: str
    path: str  # repo-relative
    line: int
    col: int
    message: str
    suggestion: Optional[str] = dataclasses.field(
        default=None, compare=False)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: " \
               f"[{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, Any]:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "col": self.col, "message": self.message}
        if self.suggestion is not None:
            out["suggestion"] = self.suggestion
        return out


@dataclasses.dataclass
class _Suppression:
    rules: Tuple[str, ...]
    reason: str
    line: int  # where the comment sits
    used: bool = False


class Module:
    """One parsed source file, shared by every rule in a run.

    ``source`` overrides the file read — the ``lint --fix`` rewriter
    re-lints its in-memory rewrite between passes without a disk
    round-trip (one construction path either way)."""

    def __init__(self, path: str, repo: str = REPO,
                 source: Optional[str] = None):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, repo).replace(os.sep, "/")
        if source is None:
            with open(self.path, encoding="utf-8") as f:
                source = f.read()
        self.source = source
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.parse_error = f"{type(e).__name__}: {e.msg} " \
                               f"(line {e.lineno})"
        self.suppressions: List[_Suppression] = self._collect_suppressions()
        #: line → suppressions covering it (own line + the next line)
        self._by_line: Dict[int, List[_Suppression]] = {}
        for sup in self.suppressions:
            for ln in (sup.line, sup.line + 1):
                self._by_line.setdefault(ln, []).append(sup)
        self._nodes: Optional[List[ast.AST]] = None
        self._functions: Optional[List[Tuple[Optional[str],
                                             ast.AST]]] = None

    def _collect_suppressions(self) -> List[_Suppression]:
        out: List[_Suppression] = []
        try:
            tokens = tokenize.generate_tokens(
                iter(self.source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m is None:
                    continue
                rules = tuple(r.strip() for r in m.group(1).split(",")
                              if r.strip())
                out.append(_Suppression(rules=rules,
                                        reason=(m.group(2) or "").strip(),
                                        line=tok.start[0]))
        except tokenize.TokenError:
            pass  # the parse-error diagnostic already covers this file
        return out

    def reset_run_state(self) -> None:
        """Clear per-run mutable state (suppression hit flags) so a
        cached Module can be reused by the next ``run_lint`` without
        carrying the previous run's usage accounting."""
        for sup in self.suppressions:
            sup.used = False

    def suppressed(self, rule: str, line: int) -> bool:
        """True (and mark used) when a VALID suppression for ``rule``
        covers ``line``. A reason-less suppression never matches — it
        surfaces as ``bad-suppression`` instead."""
        for sup in self._by_line.get(line, ()):
            if rule in sup.rules and sup.reason:
                sup.used = True
                return True
        return False

    def walk(self) -> Iterable[ast.AST]:
        """Every AST node, walked once and cached — several rules scan
        the same module; re-walking generators dominates the budget."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree)) \
                if self.tree is not None else []
        return self._nodes

    def functions(self) -> List[Tuple[Optional[str], ast.AST]]:
        """Cached ``(class_name_or_None, function_node)`` pairs."""
        if self._functions is None:
            self._functions = (list(enclosing_functions(self.tree))
                               if self.tree is not None else [])
        return self._functions


class Project:
    """The whole lint target: every parsed module plus cross-module
    indexes rules can share (built lazily, cached per run)."""

    def __init__(self, modules: List[Module], repo: str = REPO):
        self.repo = repo
        self.modules = modules
        self._cache: Dict[str, Any] = {}

    def module(self, rel: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def cached(self, key: str, build: Callable[[], Any]) -> Any:
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]


class Rule:
    """Base class for every lint rule.

    Subclasses set :attr:`id` (the suppression/CLI handle, kebab-case)
    and :attr:`rationale` (one line; ``docs/ANALYSIS.md`` catalogs it)
    and implement :meth:`check_module` and/or :meth:`check_project`.
    """

    id: str = ""
    rationale: str = ""

    def select(self, mod: Module) -> bool:
        """Whether ``mod`` is in this rule's scope (default: all)."""
        return True

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        return ()

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        return ()

    # --- helpers ------------------------------------------------------
    def diag(self, mod: Module, node: Any, message: str) -> Diagnostic:
        line = getattr(node, "lineno", node if isinstance(node, int) else 1)
        col = getattr(node, "col_offset", 0)
        return Diagnostic(rule=self.id, path=mod.rel, line=int(line),
                          col=int(col), message=message)


# --- registry ---------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a rule to the global registry."""
    if not getattr(cls, "id", ""):
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> List[Rule]:
    """Instantiate every registered rule, importing the rules package
    on first use (rules self-register via :func:`register`)."""
    from netsdb_tpu.analysis import rules as _rules  # noqa: F401

    return [cls() for _, cls in sorted(_REGISTRY.items())]


def rule_ids() -> List[str]:
    from netsdb_tpu.analysis import rules as _rules  # noqa: F401

    return sorted(_REGISTRY)


# --- running ----------------------------------------------------------

def _default_files() -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(PKG_DIR):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return out


#: parse-once cache: abs path → ((repo, mtime_ns, size), Module).
#: Parsing + the cached AST walks dominate a lint run; the tier-1
#: gate, the conftest sessionfinish re-run and every fixture test in
#: one process share parses as long as the file on disk is unchanged.
_MODULE_CACHE: Dict[str, Tuple[Tuple[str, int, int], Module]] = {}


def _load_module(path: str, repo: str) -> Module:
    abspath = os.path.abspath(path)
    try:
        st = os.stat(abspath)
        key = (repo, st.st_mtime_ns, st.st_size)
    except OSError:
        return Module(abspath, repo)  # unreadable: let open() report
    cached = _MODULE_CACHE.get(abspath)
    if cached is not None and cached[0] == key:
        # the stat key has a granularity hole: a same-size rewrite
        # within the filesystem timestamp resolution keeps the key.
        # Re-reading the source closes it — a read is ~free next to
        # the parse + AST walks the cache exists to skip
        try:
            with open(abspath, encoding="utf-8") as f:
                if f.read() == cached[1].source:
                    cached[1].reset_run_state()
                    return cached[1]
        except OSError:
            pass
    mod = Module(abspath, repo)
    _MODULE_CACHE[abspath] = (key, mod)
    return mod


def load_project(paths: Optional[Iterable[str]] = None,
                 repo: str = REPO) -> Project:
    files = list(paths) if paths is not None else _default_files()
    return Project([_load_module(p, repo) for p in files], repo)


def run_lint(paths: Optional[Iterable[str]] = None,
             rules: Optional[Iterable[str]] = None,
             repo: str = REPO,
             select_all: bool = False,
             project: Optional[Project] = None) -> List[Diagnostic]:
    """Run lint rules and return the surviving diagnostics, sorted.

    ``paths`` — explicit files (default: the whole ``netsdb_tpu/``
    package).  ``rules`` — rule ids to run (default: all).
    ``select_all`` — bypass every rule's scope filter (fixture tests run
    serve-scoped rules over files outside ``serve/``).  ``project`` —
    reuse an already-loaded :class:`Project` (and everything cached on
    it: call graph, summaries, static lock edges) instead of loading
    one; the conftest sessionfinish shares one project between the
    witness-coverage report and the lint re-run.

    Suppression accounting: ``bad-suppression`` fires on any
    suppression comment without a reason; ``unused-suppression`` fires
    only on FULL-rule-set runs (running one rule must not flag another
    rule's suppressions as stale).
    """
    if project is None:
        project = load_project(paths, repo)
    available = {r.id: r for r in all_rules()}
    if rules is None:
        chosen = list(available.values())
    else:
        unknown = [r for r in rules if r not in available]
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}; "
                             f"available: {', '.join(sorted(available))}")
        chosen = [available[r] for r in rules]
    full_run = rules is None

    diags: List[Diagnostic] = []
    for mod in project.modules:
        if mod.parse_error is not None:
            diags.append(Diagnostic(rule=PARSE_ERROR, path=mod.rel,
                                    line=1, col=0,
                                    message=mod.parse_error))
    for rule in chosen:
        for mod in project.modules:
            if mod.tree is None:
                continue
            if not (select_all or rule.select(mod)):
                continue
            for d in rule.check_module(mod):
                if not mod.suppressed(d.rule, d.line):
                    diags.append(d)
        for d in rule.check_project(project):
            m = project.module(d.path)
            if m is None or not m.suppressed(d.rule, d.line):
                diags.append(d)

    framework_ids = {BAD_SUPPRESSION, UNUSED_SUPPRESSION, PARSE_ERROR}
    for mod in project.modules:
        for sup in mod.suppressions:
            unknown_ids = [r for r in sup.rules
                           if r not in available
                           and r not in framework_ids]
            if unknown_ids:
                # a typo'd id can never match OR be reported stale —
                # without this it would accumulate silently forever
                diags.append(Diagnostic(
                    rule=BAD_SUPPRESSION, path=mod.rel, line=sup.line,
                    col=0,
                    message=f"suppression names unknown rule id(s) "
                            f"{', '.join(unknown_ids)} — typo, or a "
                            f"rule that no longer exists"))
            if not sup.reason:
                diags.append(Diagnostic(
                    rule=BAD_SUPPRESSION, path=mod.rel, line=sup.line,
                    col=0,
                    message="suppression without a reason — write "
                            "'# lint: disable=<rule> -- <why>'"))
            elif full_run and not sup.used:
                known = [r for r in sup.rules if r in available]
                if known:
                    diags.append(Diagnostic(
                        rule=UNUSED_SUPPRESSION, path=mod.rel,
                        line=sup.line, col=0,
                        message=f"suppression for "
                                f"{', '.join(sup.rules)} never matched "
                                f"a diagnostic — stale; remove it"))
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule))
    return diags


def set_gauge(name: str, value: float) -> None:
    """Export one analysis gauge through the central obs registry —
    never fatal (lint must work in environments where obs can't
    import), and in ONE place so every analysis.* gauge shares the
    same policy."""
    try:
        from netsdb_tpu.obs.metrics import registry

        registry().gauge(name).set(value)
    except Exception:  # noqa: BLE001 — obs must never break lint
        pass


def render(diags: List[Diagnostic]) -> str:
    return "\n".join(str(d) for d in diags)


def to_json(diags: List[Diagnostic]) -> List[Dict[str, Any]]:
    return [d.to_dict() for d in diags]


# --- shared AST helpers (used by several rules) -----------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last attribute/name segment of a call target or chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_keywords(node: ast.Call) -> Set[str]:
    return {kw.arg for kw in node.keywords if kw.arg is not None}


def enclosing_functions(tree: ast.AST) -> Iterable[Tuple[Optional[str],
                                                         ast.AST]]:
    """Yield ``(class_name_or_None, function_node)`` for every function
    and method in the module, including nested ones."""
    def visit(node: ast.AST, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield cls, child
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)

    yield from visit(tree, None)
