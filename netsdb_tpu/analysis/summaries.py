"""Per-function concurrency summaries over the project call graph.

The interprocedural layer between raw ASTs and the concurrency rules:
for every project function, ONE walk extracts its *facts* —

* lock tokens acquired lexically (``with <lock>:``), with sites;
* lexical nesting edges between those tokens;
* every resolved call site, annotated with the lock tokens held at
  that site (the call-through context);
* blocking calls (socket ``recv``/``accept``, ``device_put``,
  unbounded ``queue.get()``, seeded patterns), with sites;
* ``self.X`` attribute mutations (the race rule's input), annotated
  with the tokens held at the mutation site;

— then two fixpoints fold the call graph through them:

* :attr:`Summaries.trans_locks` — every lock token a function may
  acquire TRANSITIVELY (itself or any callee), each with the concrete
  acquisition site.  The lock-order rule turns "call made while
  holding T" + "callee transitively acquires L" into a T→L edge
  naming both sites, across any number of modules.
* :attr:`Summaries.trans_blocking` — every blocking call a function
  may transitively reach, depth-bounded so a diagnostics chain stays
  reviewable (a ``recv`` five layers down is an architecture note,
  not an actionable lint finding).

Token normalization (``C.attr`` / ``mod.py:name`` / ``C.rw`` rank
tokens) lives here too — it is shared by the rules, the race pass and
the witness-coverage report, and the token grammar MATCHES the
runtime witness rank names (``TrackedLock("SetStore._lock")``), which
is what makes static↔dynamic reconciliation a set comparison.

Recursion terminates by construction: both fixpoints only ever GROW
per-function sets drawn from finite universes (tokens, sites), so a
cycle in the call graph converges instead of recursing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from netsdb_tpu.analysis.callgraph import (CallGraph, FuncKey,
                                           callgraph)
from netsdb_tpu.analysis.lint import Module, Project, terminal_name

#: terminal names that denote a lock when used as ``with <expr>:``
_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|lk|mu|mutex)$|_mu$|_lock$|^mu$|^lock$")

#: constructor call names whose assignment marks ``self.X`` as a lock
LOCK_CTORS = {"Lock", "RLock", "RWLock", "TrackedLock", "TrackedRLock",
              "witness_lock"}

#: method names that block on I/O or another thread
BLOCKING_METHODS = {"recv", "recv_into", "recvmsg", "accept",
                    "device_put"}
#: seeded site-specific blocking patterns: (receiver terminal, method)
BLOCKING_SEEDED = {("po", "append")}
#: receiver terminal names treated as queues for the .get() check
_QUEUE_RECV_RE = re.compile(r"(^|_)q(ueue)?s?$|queue")

#: how many call hops a blocking site may propagate up-stack before
#: it stops contributing interprocedural findings
BLOCKING_DEPTH_CAP = 3


def is_lock_name(name: Optional[str]) -> bool:
    return bool(name) and bool(_LOCK_NAME_RE.search(name))


def lock_attr_index(project: Project) -> Dict[str, Set[str]]:
    """attr name → set of class names assigning a lock to ``self.X``
    (constructor calls and ``dataclasses.field(default_factory=
    threading.Lock)`` defaults)."""
    def build() -> Dict[str, Set[str]]:
        idx: Dict[str, Set[str]] = {}
        for mod in project.modules:
            if mod.tree is None:
                continue
            for cls_name, fn in mod.functions():
                if cls_name is None:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not assigns_lock(node.value):
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            idx.setdefault(t.attr, set()).add(cls_name)
            # dataclass fields: append_mu: Any = field(
            #     default_factory=threading.Lock)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is not None \
                            and isinstance(stmt.target, ast.Name) \
                            and _field_factory_is_lock(stmt.value):
                        idx.setdefault(stmt.target.id,
                                       set()).add(node.name)
        return idx

    return project.cached("lock_attr_index", build)


def assigns_lock(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        t = terminal_name(value.func)
        if t in LOCK_CTORS:
            return True
        return _field_factory_is_lock(value)
    return False


def _field_factory_is_lock(value: ast.AST) -> bool:
    if not (isinstance(value, ast.Call)
            and terminal_name(value.func) == "field"):
        return False
    for kw in value.keywords:
        if kw.arg != "default_factory":
            continue
        target = kw.value
        # field(default_factory=lambda: TrackedLock("rank"))
        if isinstance(target, ast.Lambda) \
                and isinstance(target.body, ast.Call):
            target = target.body.func
        if terminal_name(target) in LOCK_CTORS:
            return True
    return False


def self_path(expr: ast.AST) -> Optional[str]:
    """The dotted source path of a ``self``-rooted attribute chain
    (``self._a``, ``self._a.cache``), or None for anything else.
    Instance qualifiers and call receivers share this spelling so the
    race rule can compare them with string equality."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id == "self" and parts:
        return ".".join(["self"] + list(reversed(parts)))
    return None


def base_token(token: str) -> str:
    """Strip the instance qualifier (``C.mu@self._a`` → ``C.mu``).
    Rank consumers (lock-order edges, witness reconciliation) operate
    on lock LEVELS, where every instance of a class is one rank; only
    the race rule's coverage check is instance-sensitive."""
    return token.split("@", 1)[0]


def token_qualifier(token: str) -> Optional[str]:
    """The instance qualifier of a token (``C.mu@self._a`` →
    ``self._a``), or None for an unqualified rank."""
    if "@" in token:
        return token.split("@", 1)[1]
    return None


def lock_token(expr: ast.AST, cls: Optional[str], mod: Module,
               aliases: Dict[str, ast.AST],
               attr_index: Dict[str, Set[str]],
               _depth: int = 0) -> Optional[str]:
    """Normalize a ``with`` context expression to a rank token, or
    None when it doesn't look like a lock.

    Acquisitions through a member object (``with self._a.mu:``) carry
    an ``@self._a`` instance qualifier: ``self._a.mu`` and
    ``self._b.mu`` are the same rank but DIFFERENT locks, and the race
    rule must not let one cover mutations guarded by the other.  Bare
    ``self.mu`` stays unqualified (``C.mu``)."""
    if _depth > 3:
        return None
    # rw.read() / rw.write() → the owner class's rw rank (each
    # relation class is its own lock level; collapsing them all into
    # one "RWLock" rank mixes read-only and write-append usage of
    # DIFFERENT lock families and manufactures cycles)
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
        if expr.func.attr in ("read", "write"):
            base = expr.func.value
            bt = terminal_name(base)
            if not (bt == "rw" or (bt or "").endswith("rw")):
                return None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls:
                return f"{cls}.rw"
            owners = attr_index.get("rw", set())
            qual = self_path(base.value) \
                if isinstance(base, ast.Attribute) else None
            if len(owners) == 1:
                tok = f"{next(iter(owners))}.rw"
            else:
                tok = "*.rw"  # ambiguous owner: contributes no edges
            return f"{tok}@{qual}" if qual else tok
        # self._set_lock(db, s) style: a method returning a lock
        if is_lock_name(expr.func.attr) or expr.func.attr.endswith(
                ("_lock", "_mu")):
            owner = None
            if isinstance(expr.func.value, ast.Name) \
                    and expr.func.value.id == "self" and cls:
                owner = cls
            name = expr.func.attr
            # the per-set-lock idiom: a getter named _set_lock maps to
            # the instance-family rank C._set_locks[]
            if name.startswith("_set_lock"):
                return f"{owner or '*'}._set_locks[]"
            return f"{owner or '*'}.{name}()"
        return None
    if isinstance(expr, ast.Call):  # Lock() inline — anonymous, skip
        return None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
        if not is_lock_name(name):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            return f"{cls}.{name}"
        owners = attr_index.get(name, set())
        if len(owners) == 1:
            tok = f"{next(iter(owners))}.{name}"
        else:
            tok = f"*.{name}"
        qual = self_path(base)
        return f"{tok}@{qual}" if qual else tok
    if isinstance(expr, ast.Name):
        if expr.id in aliases:
            return lock_token(aliases[expr.id], cls, mod, aliases,
                              attr_index, _depth + 1)
        if is_lock_name(expr.id):
            return f"{mod.rel}:{expr.id}"
        return None
    return None


def token_owner(token: str) -> Optional[str]:
    """The owner-class prefix of a rank token (``SetStore._lock`` →
    ``SetStore``), or None for module-level / wildcard tokens."""
    if token.startswith("*.") or ":" in token:
        return None
    return token.split(".", 1)[0] if "." in token else None


def blocking_what(call: ast.Call) -> Optional[str]:
    """The human label of a blocking call, or None. Shared by the
    lexical rule and the transitive summary so the two can never
    disagree about what counts as blocking."""
    f = call.func
    name = terminal_name(f)
    if name is None:
        return None
    recv = terminal_name(f.value) if isinstance(f, ast.Attribute) \
        else None
    if name in BLOCKING_METHODS:
        return f"{name}()"
    if recv is not None and (recv, name) in BLOCKING_SEEDED:
        return f"{recv}.{name}() (PagedObjects.append waits on " \
               f"the relation's stream locks)"
    if name == "get" and recv is not None \
            and _QUEUE_RECV_RE.search(recv):
        kws = {kw.arg for kw in call.keywords}
        nonblocking = "timeout" in kws or any(
            kw.arg == "block" and isinstance(kw.value, ast.Constant)
            and kw.value.value is False for kw in call.keywords) \
            or len(call.args) >= 2 \
            or (len(call.args) == 1 and isinstance(
                call.args[0], ast.Constant)
                and call.args[0].value is False)
        if not nonblocking:
            return f"{recv}.get() without a timeout"
    return None


class CallSite:
    """One resolved call, with the lock context held at the site.

    ``receiver`` is the dotted ``self``-rooted path of the call's
    receiver (``self._a.step()`` → ``"self._a"``), or None — the race
    rule matches it against instance qualifiers on held tokens to
    decide whether a member-object lock covers the callee subtree."""

    __slots__ = ("callee", "line", "held", "receiver")

    def __init__(self, callee: FuncKey, line: int,
                 held: Tuple[str, ...],
                 receiver: Optional[str] = None):
        self.callee = callee
        self.line = line
        self.held = held
        self.receiver = receiver


class FnFacts:
    """One function's directly-observable concurrency facts."""

    __slots__ = ("key", "acquired", "lex_edges", "calls", "blocking",
                 "mutations")

    def __init__(self, key: FuncKey):
        self.key = key
        #: token → (rel, line) of the first lexical acquisition
        self.acquired: Dict[str, Tuple[str, int]] = {}
        #: (outer, inner, line) lexical nesting edges
        self.lex_edges: List[Tuple[str, str, int]] = []
        #: resolved call sites with held-lock context
        self.calls: List[CallSite] = []
        #: (what, line, held-at-site) direct blocking calls
        self.blocking: List[Tuple[str, int, Tuple[str, ...]]] = []
        #: (attr, line, held-at-site) ``self.X`` mutations
        self.mutations: List[Tuple[str, int, Tuple[str, ...]]] = []


class Summaries:
    """All per-function facts plus the transitive fixpoints."""

    def __init__(self, project: Project, graph: CallGraph):
        self.project = project
        self.graph = graph
        self.attr_index = lock_attr_index(project)
        self.facts: Dict[FuncKey, FnFacts] = {}
        for info in graph.functions.values():
            self.facts[info.key] = self._collect(info)
        #: token → (rel, line): every token a function may acquire
        #: transitively, with the CONCRETE acquisition site
        self.trans_locks: Dict[FuncKey,
                               Dict[str, Tuple[str, int]]] = {}
        #: what → (rel, line, depth): transitively reachable blocking
        #: calls, depth 0 = in the function itself
        self.trans_blocking: Dict[FuncKey,
                                  Dict[str, Tuple[str, int, int]]] = {}
        self._fix_locks()
        self._fix_blocking()

    # --- single-function walk ----------------------------------------
    def _collect(self, info) -> FnFacts:
        facts = FnFacts(info.key)
        mod, cls, fn = info.mod, info.cls, info.node
        aliases = info.aliases()

        def tok(expr: ast.AST) -> Optional[str]:
            return lock_token(expr, cls, mod, aliases, self.attr_index)

        # explicit ``X.acquire()`` calls (the try/finally idiom a
        # ``with`` cannot express, e.g. around a generator yield):
        # conservatively held from the acquire line to function end
        explicit: List[Tuple[str, int]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                t = tok(node.func.value)
                if t is not None:
                    explicit.append((t, node.lineno))
                    facts.acquired.setdefault(t, (mod.rel,
                                                  node.lineno))

        def full_held(node: ast.AST,
                      held: List[Tuple[str, int]]) -> Tuple[str, ...]:
            line = getattr(node, "lineno", 0)
            toks = [t for t, _ in held]
            toks += [t for t, al in explicit
                     if al < line and t not in toks]
            return tuple(toks)

        def note_call(node: ast.Call, held: List[Tuple[str, int]]):
            callee = self.graph.resolve(mod, cls, node.func, aliases)
            held_toks = full_held(node, held)
            if callee is not None:
                receiver = self_path(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                facts.calls.append(CallSite(callee, node.lineno,
                                            held_toks, receiver))
            what = blocking_what(node)
            if what is not None:
                facts.blocking.append((what, node.lineno, held_toks))

        def flat_targets(t: ast.AST):
            # tuple/list unpacking: self.a, self.b = ... mutates both
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    yield from flat_targets(el)
            elif isinstance(t, ast.Starred):
                yield from flat_targets(t.value)
            else:
                yield t

        def note_mutation(node: ast.AST, held: List[Tuple[str, int]]):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                targets = [node.target]
            held_toks = full_held(node, held)
            for raw in targets:
                for t in flat_targets(raw):
                    # self.X = / self.X[k] = — unwrap one subscript
                    if isinstance(t, ast.Subscript):
                        t = t.value
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        facts.mutations.append((t.attr, node.lineno,
                                                held_toks))

        def visit(node: ast.AST, held: List[Tuple[str, int]]):
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
                return  # nested defs get their own FnFacts
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = list(held)
                for item in node.items:
                    # the context expression evaluates under OUTER
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            note_call(sub, held)
                    t = tok(item.context_expr)
                    if t is None:
                        continue
                    facts.acquired.setdefault(
                        t, (mod.rel, item.context_expr.lineno))
                    outers = [o for o, _line in new_held]
                    outers += [o for o, al in explicit
                               if al < item.context_expr.lineno
                               and o not in outers]
                    for outer in outers:
                        if outer != t:  # re-entrant same-rank: no edge
                            facts.lex_edges.append(
                                (outer, t, item.context_expr.lineno))
                    new_held.append((t, item.context_expr.lineno))
                for sub in node.body:
                    visit(sub, new_held)
                return
            if isinstance(node, ast.Call):
                note_call(node, held)
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                note_mutation(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(fn, [])
        return facts

    # --- fixpoints ----------------------------------------------------
    def _fix_locks(self) -> None:
        for key, facts in self.facts.items():
            self.trans_locks[key] = dict(facts.acquired)
        changed = True
        while changed:
            changed = False
            for key, facts in self.facts.items():
                mine = self.trans_locks[key]
                for site in facts.calls:
                    for tok_, where in self.trans_locks.get(
                            site.callee, {}).items():
                        if tok_ not in mine:
                            mine[tok_] = where
                            changed = True

    def _fix_blocking(self) -> None:
        for key, facts in self.facts.items():
            self.trans_blocking[key] = {
                what: (key[0], line, 0)
                for what, line, _held in facts.blocking}
        changed = True
        while changed:
            changed = False
            for key, facts in self.facts.items():
                mine = self.trans_blocking[key]
                for site in facts.calls:
                    for what, (rel, line, depth) in \
                            self.trans_blocking.get(site.callee,
                                                    {}).items():
                        if depth + 1 > BLOCKING_DEPTH_CAP:
                            continue
                        cur = mine.get(what)
                        if cur is None or depth + 1 < cur[2]:
                            mine[what] = (rel, line, depth + 1)
                            changed = True


def summaries(project: Project) -> Summaries:
    """The per-run shared instance (built once, cached)."""
    return project.cached(
        "summaries", lambda: Summaries(project, callgraph(project)))
