"""Project-wide call graph — the interprocedural substrate every
concurrency rule now stands on.

PR 8's rules saw one module at a time: a ``with`` in
``serve/server.py`` that calls into ``storage/devcache.py`` which
takes another tracked lock was invisible, and the ROADMAP carried
"cross-MODULE call-through edges" ever since.  This module closes
that: one :class:`CallGraph` per lint run resolving every call site
to the project function it lands in —

* **module imports** — ``import netsdb_tpu.storage.devcache as dc``
  then ``dc.to_device(...)``; ``from netsdb_tpu.plan import staging``
  then ``staging.stage_stream(...)``; dotted chains through package
  ``__init__`` re-exports fall back to a unique-stem match;
* **methods** — ``self.m(...)`` resolves through the enclosing class
  and its project-visible base classes (bounded MRO walk);
  ``ClassName.m(...)`` and ``ClassName(...)`` (constructor →
  ``__init__``);
* **attribute types** — ``self._store.add_data(...)`` resolves via
  the attribute-type index (``self._store = SetStore(...)`` in any
  method of the class names the attr's type; a globally unique owner
  also resolves) — the edge that carries serve/ analysis into
  storage/;
* **one-hop local aliases** — ``fn = self._worker; Thread(target=
  fn)`` and ``st = SetStore(cfg); st.add_data(...)``;
* **``functools.partial``** — unwrapped to its first argument.

On top of resolution the graph derives **thread roots**: every
``threading.Thread(target=...)`` / executor ``submit(...)`` target,
i.e. the entry points whose transitive reachability sets define
"which code can run concurrently with what" — the input to the
static race rule and the witness-coverage report.

Everything is stdlib ``ast``; the graph is built once per
:class:`~netsdb_tpu.analysis.lint.Project` (``project.cached``) and
shared by every rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from netsdb_tpu.analysis.lint import (Module, Project, dotted_name,
                                      set_gauge, terminal_name)

#: (module rel path, enclosing class or None, function name) — the
#: identity of one project function; nested defs share the scheme
#: (their enclosing CLASS, not function, is the second element)
FuncKey = Tuple[str, Optional[str], str]


def fmt_key(key: FuncKey) -> str:
    rel, cls, name = key
    return f"{rel}:{cls + '.' if cls else ''}{name}"


class FuncInfo:
    """One project function: where it lives and its AST node."""

    __slots__ = ("key", "mod", "cls", "node", "_aliases")

    def __init__(self, key: FuncKey, mod: Module, cls: Optional[str],
                 node: ast.AST):
        self.key = key
        self.mod = mod
        self.cls = cls
        self.node = node
        self._aliases: Optional[Dict[str, ast.AST]] = None

    def aliases(self) -> Dict[str, ast.AST]:
        """The one-hop local alias map, computed once and shared by
        every pass that resolves this function's call sites (edge
        build, thread roots, summaries)."""
        if self._aliases is None:
            self._aliases = local_aliases(self.node)
        return self._aliases


class ThreadRoot:
    """One concurrent entry point: the resolved target function plus
    every spawn site that launches it."""

    __slots__ = ("key", "sites", "kind")

    def __init__(self, key: FuncKey, kind: str):
        self.key = key
        self.kind = kind  # "thread" | "executor"
        self.sites: List[Tuple[str, int]] = []


def local_aliases(fn: ast.AST) -> Dict[str, ast.AST]:
    """name → RHS for single-target simple assignments in ``fn`` —
    the one-hop alias resolver (``lk = self._set_lock(...)``,
    ``fn = self._worker``)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, (ast.Attribute, ast.Call,
                                            ast.Name)):
            name = node.targets[0].id
            # a name assigned twice is not a stable alias
            out[name] = None if name in out else node.value
    return {k: v for k, v in out.items() if v is not None}


def own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """``fn``'s nodes EXCLUDING nested def/class subtrees — nested
    functions are project functions of their own."""
    stack = [fn]
    while stack:
        node = stack.pop()
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            yield node  # the def node itself (for parent→nested edges)
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Resolution indexes + resolved call edges + thread roots."""

    def __init__(self, project: Project):
        self.project = project
        #: FuncKey → FuncInfo for every function/method in the tree
        self.functions: Dict[FuncKey, FuncInfo] = {}
        #: module rel → {local name: dotted module} (import ... as)
        self._imports: Dict[str, Dict[str, str]] = {}
        #: module rel → {local name: (dotted module, original name)}
        self._from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        #: module rel → {class name: [base name strings]}
        self._classes: Dict[str, Dict[str, List[str]]] = {}
        #: class name → [module rels defining it]
        self._class_owners: Dict[str, List[str]] = {}
        #: (module rel, class) → {attr: {type class names}} from
        #: ``self.attr = ClassName(...)`` assignments
        self._attr_types: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        #: attr name → {type class names} across the whole project
        self._attr_types_global: Dict[str, Set[str]] = {}
        #: dotted module name → rel path (built lazily)
        self._mod_by_dotted: Dict[str, Optional[str]] = {}
        #: stem (filename sans .py) → [rel paths]
        self._mod_by_stem: Dict[str, List[str]] = {}
        #: caller → [(callee, line)] resolved call edges (lock
        #: context lives in summaries, not here)
        self.calls: Dict[FuncKey, List[Tuple[FuncKey, int]]] = {}
        #: resolved concurrent entry points
        self.thread_roots: Dict[FuncKey, ThreadRoot] = {}
        #: id(expr) → resolution, memoized across the three passes
        #: that visit the same call nodes (edge build, thread roots,
        #: summaries). Safe because an expression node belongs to
        #: exactly one function, so its (cls, aliases) context is
        #: fixed — and the nodes stay alive as long as the cached
        #: Module (and therefore this graph) does.
        self._resolve_memo: Dict[int, Optional[FuncKey]] = {}
        self._build_indexes()
        self._build_edges()
        self._find_thread_roots()

    # --- indexes ------------------------------------------------------
    def _build_indexes(self) -> None:
        for mod in self.project.modules:
            if mod.rel.endswith(".py"):
                stem = mod.rel.rsplit("/", 1)[-1][:-3]
                self._mod_by_stem.setdefault(stem, []).append(mod.rel)
            if mod.tree is None:
                continue
            imps: Dict[str, str] = {}
            frm: Dict[str, Tuple[str, str]] = {}
            for node in mod.walk():
                if isinstance(node, ast.Import):
                    for a in node.names:
                        local = a.asname or a.name.split(".")[0]
                        # ``import a.b`` binds ``a`` but the useful
                        # target is the full dotted path — keep both
                        imps[local] = a.name if a.asname else \
                            a.name.split(".")[0]
                        if a.asname is None:
                            imps.setdefault(a.name, a.name)
                elif isinstance(node, ast.ImportFrom) and node.module \
                        and node.level == 0:
                    for a in node.names:
                        if a.name == "*":
                            continue
                        frm[a.asname or a.name] = (node.module, a.name)
            self._imports[mod.rel] = imps
            self._from_imports[mod.rel] = frm
            classes: Dict[str, List[str]] = {}
            for node in mod.walk():
                if isinstance(node, ast.ClassDef):
                    bases = [dotted_name(b) or "" for b in node.bases]
                    classes[node.name] = [b for b in bases if b]
                    self._class_owners.setdefault(
                        node.name, []).append(mod.rel)
            self._classes[mod.rel] = classes
            for cls, fn in mod.functions():
                key = (mod.rel, cls, fn.name)
                # first definition wins on (rare) collisions between a
                # nested def and a module-level function of one name
                if key not in self.functions:
                    self.functions[key] = FuncInfo(key, mod, cls, fn)
                if cls is None:
                    continue
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    t = node.targets[0]
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    tname = self._ctor_class_name(mod, node.value)
                    if tname is None:
                        continue
                    self._attr_types.setdefault(
                        (mod.rel, cls), {}).setdefault(
                        t.attr, set()).add(tname)
                    self._attr_types_global.setdefault(
                        t.attr, set()).add(tname)

    def _ctor_class_name(self, mod: Module,
                         value: ast.AST) -> Optional[str]:
        """``ClassName(...)`` (possibly dotted) → the class name when
        it resolves to a project class."""
        if not isinstance(value, ast.Call):
            return None
        name = terminal_name(value.func)
        if name and name in self._class_owners:
            return name
        return None

    def _resolve_module(self, dotted: str) -> Optional[str]:
        """Dotted module name → project rel path, or None."""
        if dotted in self._mod_by_dotted:
            return self._mod_by_dotted[dotted]
        rel = None
        as_path = dotted.replace(".", "/")
        for cand in (as_path + ".py", as_path + "/__init__.py"):
            if self.project.module(cand) is not None:
                rel = cand
                break
        if rel is None:
            # fixtures / flat trees: a unique filename-stem match
            stem = dotted.rsplit(".", 1)[-1]
            owners = self._mod_by_stem.get(stem, ())
            if len(owners) == 1:
                rel = owners[0]
        self._mod_by_dotted[dotted] = rel
        return rel

    def _class_rel(self, cls_name: str,
                   prefer_rel: Optional[str] = None) -> Optional[str]:
        owners = self._class_owners.get(cls_name, ())
        if prefer_rel is not None and prefer_rel in owners:
            return prefer_rel
        if len(owners) == 1:
            return owners[0]
        return None

    def _method(self, rel: str, cls_name: str, name: str,
                _depth: int = 0) -> Optional[FuncKey]:
        """Find method ``name`` on class ``cls_name`` (defined in
        ``rel``), walking project-visible base classes, bounded."""
        if _depth > 4:
            return None
        key = (rel, cls_name, name)
        if key in self.functions:
            return key
        for base in self._classes.get(rel, {}).get(cls_name, ()):  # MRO
            base_name = base.rsplit(".", 1)[-1]
            base_rel = self._class_rel(base_name, prefer_rel=rel)
            if base_rel is None:
                # ``devcache.DeviceBlockCache`` style dotted base
                if "." in base:
                    mod_rel = self._resolve_by_prefix(
                        rel, base.rsplit(".", 1)[0])
                    if mod_rel and (mod_rel, base_name, name) \
                            in self.functions:
                        return (mod_rel, base_name, name)
                continue
            found = self._method(base_rel, base_name, name, _depth + 1)
            if found is not None:
                return found
        return None

    def _resolve_by_prefix(self, rel: str,
                           prefix: str) -> Optional[str]:
        """A dotted prefix (``dc`` / ``netsdb_tpu.plan.staging``)
        seen in module ``rel`` → the module it names, via the import
        maps then the literal dotted path."""
        imps = self._imports.get(rel, {})
        frm = self._from_imports.get(rel, {})
        head = prefix.split(".")[0]
        if prefix in imps:
            return self._resolve_module(imps[prefix])
        if head in imps and head != prefix:
            return self._resolve_module(
                imps[head] + "." + prefix.split(".", 1)[1])
        if prefix in frm:
            dotted_mod, orig = frm[prefix]
            return self._resolve_module(dotted_mod + "." + orig)
        if head in frm and head != prefix:
            dotted_mod, orig = frm[head]
            return self._resolve_module(
                dotted_mod + "." + orig + "." + prefix.split(".", 1)[1])
        return self._resolve_module(prefix)

    # --- call-site resolution -----------------------------------------
    def resolve(self, mod: Module, cls: Optional[str], expr: ast.AST,
                aliases: Dict[str, ast.AST],
                _depth: int = 0) -> Optional[FuncKey]:
        """Resolve a callable expression (a ``Call.func`` or a
        ``target=`` value) to a project :data:`FuncKey`, or None for
        stdlib / unresolvable targets."""
        if _depth == 0:
            memo_key = id(expr)
            if memo_key in self._resolve_memo:
                return self._resolve_memo[memo_key]
            out = self.resolve(mod, cls, expr, aliases, _depth=1)
            self._resolve_memo[memo_key] = out
            return out
        if _depth > 4:
            return None
        # functools.partial(f, ...) → f
        if isinstance(expr, ast.Call) \
                and terminal_name(expr.func) == "partial" and expr.args:
            return self.resolve(mod, cls, expr.args[0], aliases,
                                _depth + 1)
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in aliases:
                return self.resolve(mod, cls, aliases[name], aliases,
                                    _depth + 1)
            if (mod.rel, None, name) in self.functions:
                return (mod.rel, None, name)
            if name in self._classes.get(mod.rel, {}):
                return self._method(mod.rel, name, "__init__")
            frm = self._from_imports.get(mod.rel, {})
            if name in frm:
                dotted_mod, orig = frm[name]
                target_rel = self._resolve_module(dotted_mod)
                if target_rel is not None:
                    if (target_rel, None, orig) in self.functions:
                        return (target_rel, None, orig)
                    if orig in self._classes.get(target_rel, {}):
                        return self._method(target_rel, orig, "__init__")
                # ``from pkg import name`` re-exported through
                # __init__: fall back to a unique project class
                rel2 = self._class_rel(orig)
                if rel2 is not None:
                    return self._method(rel2, orig, "__init__")
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        name = expr.attr
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                found = self._method(mod.rel, cls, name)
                if found is not None:
                    return found
                # self._attr used as a callable (bound method alias)
                return None
            # ClassName.m(...)
            if base.id in self._classes.get(mod.rel, {}):
                return self._method(mod.rel, base.id, name)
            # local var of known constructor type: st = SetStore(...)
            if base.id in aliases:
                tname = self._alias_type(mod, cls, aliases[base.id],
                                         aliases)
                if tname is not None:
                    rel2 = self._class_rel(tname)
                    if rel2 is not None:
                        return self._method(rel2, tname, name)
                return None
            # imported module (or class) attribute
            target_rel = self._resolve_by_prefix(mod.rel, base.id)
            if target_rel is not None:
                if (target_rel, None, name) in self.functions:
                    return (target_rel, None, name)
                if name in self._classes.get(target_rel, {}):
                    return self._method(target_rel, name, "__init__")
            frm = self._from_imports.get(mod.rel, {})
            if base.id in frm:  # ``from x import C`` then ``C.m(...)``
                _mod, orig = frm[base.id]
                rel2 = self._class_rel(orig)
                if rel2 is not None:
                    return self._method(rel2, orig, name)
            return None
        if isinstance(base, ast.Attribute):
            # self.X.m(...) via the attribute-type index
            if isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls is not None:
                owners = self._attr_types.get(
                    (mod.rel, cls), {}).get(base.attr)
                if not owners:
                    owners = self._attr_types_global.get(base.attr)
                if owners and len(owners) == 1:
                    tname = next(iter(owners))
                    rel2 = self._class_rel(tname)
                    if rel2 is not None:
                        return self._method(rel2, tname, name)
                return None
            # a.b.f(...) where a.b names an imported module
            prefix = dotted_name(base)
            if prefix is not None:
                target_rel = self._resolve_by_prefix(mod.rel, prefix)
                if target_rel is not None:
                    if (target_rel, None, name) in self.functions:
                        return (target_rel, None, name)
                    if name in self._classes.get(target_rel, {}):
                        return self._method(target_rel, name,
                                            "__init__")
        return None

    def _alias_type(self, mod: Module, cls: Optional[str],
                    rhs: ast.AST,
                    aliases: Dict[str, ast.AST]) -> Optional[str]:
        """The class name a one-hop alias RHS constructs, if any."""
        if isinstance(rhs, ast.Call):
            tname = terminal_name(rhs.func)
            if tname and tname in self._class_owners:
                return tname
        return None

    # --- edges --------------------------------------------------------
    def _build_edges(self) -> None:
        for info in self.functions.values():
            aliases = info.aliases()
            edges: List[Tuple[FuncKey, int]] = []
            for node in own_nodes(info.node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not info.node:
                    # a nested def is conservatively reachable from
                    # its parent (closures are usually invoked within
                    # or handed to workers the roots pass sees)
                    nested = (info.mod.rel, info.cls, node.name)
                    if nested in self.functions:
                        edges.append((nested, node.lineno))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve(info.mod, info.cls, node.func,
                                      aliases)
                if callee is not None:
                    edges.append((callee, node.lineno))
                # callable ARGUMENTS of project functions are treated
                # as potentially invoked by the callee (stage_stream's
                # place fn, executor-style helpers)
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        cb = self.resolve(info.mod, info.cls, arg,
                                          aliases)
                        if cb is not None and cb != callee:
                            edges.append((cb, node.lineno))
            self.calls[info.key] = edges

    # --- thread roots -------------------------------------------------
    def _find_thread_roots(self) -> None:
        for info in self.functions.values():
            aliases = info.aliases()
            for node in own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                tname = terminal_name(node.func)
                target: Optional[ast.AST] = None
                kind = None
                if tname == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target, kind = kw.value, "thread"
                elif tname == "submit" \
                        and isinstance(node.func, ast.Attribute) \
                        and node.args:
                    target, kind = node.args[0], "executor"
                if target is None:
                    continue
                key = self.resolve(info.mod, info.cls, target, aliases)
                if key is None:
                    continue
                root = self.thread_roots.get(key)
                if root is None:
                    root = self.thread_roots[key] = ThreadRoot(key,
                                                               kind)
                root.sites.append((info.mod.rel, node.lineno))

    # --- queries ------------------------------------------------------
    # NOTE: thread-root reachability deliberately lives in
    # rules/races.py (its traversal needs the construction barrier
    # and covered-site pruning); a raw barrier-less reachability here
    # would be a trap for future callers.
    def edge_count(self) -> int:
        return sum(len(v) for v in self.calls.values())


def callgraph(project: Project) -> CallGraph:
    """The per-run shared instance (built once, cached)."""
    def build() -> CallGraph:
        graph = CallGraph(project)
        set_gauge("analysis.callgraph_edges", graph.edge_count())
        return graph

    return project.cached("callgraph", build)
