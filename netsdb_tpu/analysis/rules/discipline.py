"""Ports of the pre-framework static checks, one typed rule each.

Every check that lived as a bespoke scanner in
``tests/test_static_checks.py`` (clock discipline, exception taxonomy,
zero-copy framing, pickle confinement, staging/device-upload
discipline, print ban, qid minting, obs counter discipline) is now a
:class:`~netsdb_tpu.analysis.lint.Rule` with the same scope and the
same failure text intent — plus per-rule inline suppressions, which
the old scanners could not express (their exemptions were hardwired
file lists; those lists live on here as rule scope).
"""

from __future__ import annotations

import ast
from typing import Iterable

from netsdb_tpu.analysis.lint import (Diagnostic, Module, Rule,
                                      register, terminal_name)

_SERVE = "netsdb_tpu/serve/"
_OBS = "netsdb_tpu/obs/"
_PLAN = "netsdb_tpu/plan/"
_STORAGE = "netsdb_tpu/storage/"
_OOC = "netsdb_tpu/relational/outofcore.py"

#: the staging module owns the (background-thread) device_put calls
_STAGING_EXEMPT = ("netsdb_tpu/plan/staging.py",)
#: the two modules allowed to name device_put on storage/plan paths
_UPLOAD_EXEMPT = ("netsdb_tpu/plan/staging.py",
                  "netsdb_tpu/storage/devcache.py")
#: protocol.py metadata codec — the only pickle-allowed functions
_PICKLE_OK_FUNCS = {"encode_body", "decode_body"}
#: print() is the OUTPUT of these (operator CLI / bench scripts)
_PRINT_EXEMPT = ("netsdb_tpu/cli.py", "netsdb_tpu/_reexec.py")
_PRINT_EXEMPT_DIRS = ("netsdb_tpu/workloads/",)

_LOOP_NODES = (ast.For, ast.While, ast.AsyncFor, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)


@register
class WallClockRule(Rule):
    """``time.time()`` in deadline-bearing layers (serve/, obs/)."""

    id = "wall-clock"
    rationale = ("wall clocks jump (NTP); every deadline must be "
                 "time.monotonic(), display stamps via "
                 "utils.timing.wall_now")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith((_SERVE, _OBS))

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "time" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "time":
                yield self.diag(
                    mod, node,
                    "time.time() in a deadline-bearing layer — use "
                    "time.monotonic() (display: utils.timing.wall_now)")
            if isinstance(node, ast.ImportFrom) and node.module == "time" \
                    and any(a.name == "time" for a in node.names):
                yield self.diag(
                    mod, node,
                    "'from time import time' hides wall-clock reads "
                    "from review")


@register
class BroadExceptRule(Rule):
    """Broad except handlers that neither bind nor re-raise."""

    id = "broad-except"
    rationale = ("an opaque except erases the typed error taxonomy — "
                 "bind ('as e') and forward, or re-raise")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith((_SERVE, _OBS))

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException"))
            reraises = any(isinstance(sub, ast.Raise)
                           for sub in ast.walk(node))
            if broad and node.name is None and not reraises:
                yield self.diag(
                    mod, node,
                    "broad except that neither binds ('as e') nor "
                    "re-raises — type it or forward it "
                    "(serve/errors.py)")


@register
class ToBytesRule(Rule):
    """``.tobytes()`` on the serve data path (breaks zero-copy v3)."""

    id = "tobytes"
    rationale = ("tensor bytes ride out-of-band memoryview segments; "
                 "one .tobytes() reintroduces the full-payload copy")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith(_SERVE)

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "tobytes":
                yield self.diag(
                    mod, node,
                    ".tobytes() on the serve data path — ship the "
                    "buffer as an out-of-band segment (memoryview), "
                    "never a copy")


@register
class PickleProtocolRule(Rule):
    """pickle/cloudpickle outside protocol.py's metadata codec."""

    id = "pickle-protocol"
    rationale = ("tensor bytes must never ride a pickle stream; the "
                 "wire's pickle use is confined to the metadata codec")

    def select(self, mod: Module) -> bool:
        return mod.rel == _SERVE + "protocol.py"

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in _PICKLE_OK_FUNCS:
                    continue
                if self._mentions_pickle(node):
                    yield self.diag(
                        mod, node,
                        f"pickle use in {node.name}() — allowed only "
                        f"in the metadata codec "
                        f"({', '.join(sorted(_PICKLE_OK_FUNCS))})")
            elif self._mentions_pickle(node):
                yield self.diag(
                    mod, node,
                    "module-level pickle reference in the wire "
                    "protocol — allowed only inside the metadata "
                    "codec functions")

    @staticmethod
    def _mentions_pickle(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) \
                    and sub.id in ("pickle", "cloudpickle"):
                return True
            if isinstance(sub, (ast.Import, ast.ImportFrom)):
                names = [a.name for a in sub.names]
                if isinstance(sub, ast.ImportFrom) and sub.module:
                    names.append(sub.module)
                if any(n.split(".")[0] in ("pickle", "cloudpickle")
                       for n in names):
                    return True
        return False


@register
class DevicePutLoopRule(Rule):
    """Synchronous ``device_put`` inside loop bodies on the streamed
    hot paths (plan/, outofcore)."""

    id = "device-put-loop"
    rationale = ("per-chunk uploads go through plan/staging."
                 "stage_stream so the copy overlaps compute")

    def select(self, mod: Module) -> bool:
        if mod.rel in _STAGING_EXEMPT:
            return False
        return mod.rel.startswith(_PLAN) or mod.rel == _OOC

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for loop in mod.walk():
            if not isinstance(loop, _LOOP_NODES):
                continue
            for sub in ast.walk(loop):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "device_put":
                    yield self.diag(
                        mod, sub,
                        "synchronous device_put inside a loop body — "
                        "stage uploads through plan/staging."
                        "stage_stream so the copy overlaps the "
                        "consumer's compute")


@register
class DevicePutDirectRule(Rule):
    """Any ``device_put`` mention on storage/plan paths outside the
    sanctioned upload modules (cache bypass)."""

    id = "device-put-direct"
    rationale = ("store-owned block uploads go through storage/"
                 "devcache.to_device or the cross-query cache is "
                 "silently bypassed and its counters lie")

    def select(self, mod: Module) -> bool:
        if mod.rel in _UPLOAD_EXEMPT:
            return False
        return mod.rel.startswith((_STORAGE, _PLAN)) or mod.rel == _OOC

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            hit = None
            if isinstance(node, ast.Call):
                if terminal_name(node.func) == "device_put":
                    hit = "call"
            elif isinstance(node, ast.ImportFrom):
                if any(a.name == "device_put" for a in node.names):
                    hit = "import"
            if hit:
                yield self.diag(
                    mod, node,
                    f"direct device_put ({hit}) on a store/plan path "
                    f"— upload set blocks via storage/devcache."
                    f"to_device (inside a stage_stream place "
                    f"function) so the device cache cannot be "
                    f"silently bypassed")


@register
class ModuleDictCounterRule(Rule):
    """Module-level dict literals in obs/ (counters belong to the
    registry)."""

    id = "module-dict-counter"
    rationale = ("a bare module dict is invisible to COLLECT_STATS "
                 "and un-resettable; counters go through "
                 "MetricsRegistry")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith(_OBS)

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.tree.body:
            targets, value = [], None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is not None \
                    and isinstance(value, (ast.Dict, ast.DictComp)):
                names = ", ".join(getattr(t, "id", "?") for t in targets)
                yield self.diag(
                    mod, node,
                    f"module-level dict {names!r} in obs/ — counters "
                    f"go through MetricsRegistry, not bare module "
                    f"dicts")


@register
class PrintBanRule(Rule):
    """``print()`` outside cli.py / workloads / _reexec."""

    id = "print-ban"
    rationale = ("daemons and libraries report through the logger or "
                 "the metrics registry, never stdout")

    def select(self, mod: Module) -> bool:
        if not mod.rel.startswith("netsdb_tpu/"):
            return False
        if mod.rel in _PRINT_EXEMPT:
            return False
        return not mod.rel.startswith(_PRINT_EXEMPT_DIRS)

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                yield self.diag(
                    mod, node,
                    "print() outside cli.py/workloads/ — use "
                    "utils.profiling.get_logger or a registry counter")


@register
class RowwiseShadowRule(Rule):
    """Manual ``rowwise=True`` declarations on Apply labels the
    derived registry already covers."""

    id = "rowwise-shadow"
    rationale = ("plan/computations.ROWWISE_SAFE_LABELS is the one "
                 "source of truth for the suite's audited "
                 "row-decomposable transforms; a per-node re-"
                 "declaration shadows it and drifts when the registry "
                 "is re-audited")

    def select(self, mod: Module) -> bool:
        return mod.rel.endswith(".py") \
            and not mod.rel.startswith("tests/fixtures/")

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        # the registry lives in a jax-free module, importable from the
        # lint process (the framework bans jax imports at lint time)
        from netsdb_tpu.plan.computations import rowwise_safe

        for node in mod.walk():
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "Apply"):
                continue
            kw = {k.arg: k.value for k in node.keywords
                  if k.arg is not None}
            rw = kw.get("rowwise")
            label = kw.get("label")
            if (isinstance(rw, ast.Constant) and rw.value is True
                    and isinstance(label, ast.Constant)
                    and isinstance(label.value, str)
                    and rowwise_safe(label.value)):
                yield self.diag(
                    mod, node,
                    f"rowwise=True on label {label.value!r} shadows "
                    f"the derived registry (plan/computations."
                    f"ROWWISE_SAFE_LABELS) — drop the argument; the "
                    f"declaration is auto-derived")


#: the two modules allowed to touch per-session device-cache state:
#: the owner (serve/sessions.py drives every install/update/spill
#: decision) and the cache that implements the primitives
_SESSION_STATE_EXEMPT = ("netsdb_tpu/serve/sessions.py",
                         "netsdb_tpu/storage/devcache.py")
#: the session-state mutators (devcache session API + spill wiring)
_SESSION_STATE_CALLS = ("session_put", "session_update",
                        "session_drop", "session_sweep",
                        "set_session_spill")


@register
class SessionStateMutationRule(Rule):
    """Per-session device-cache state mutated outside the session
    manager (breaks step-tag consistency and the TTL accounting)."""

    id = "session-state-mutation"
    rationale = ("session state carries step tags and TTL/LRU "
                 "accounting that only serve/sessions.py maintains "
                 "coherently; a stray session_put desyncs the "
                 "devcache copy from the arena spill and tears "
                 "revived state")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith("netsdb_tpu/") \
            and mod.rel not in _SESSION_STATE_EXEMPT

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            name = None
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                if t in _SESSION_STATE_CALLS:
                    name = t
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name in _SESSION_STATE_CALLS:
                        name = a.name
                        break
            if name:
                yield self.diag(
                    mod, node,
                    f"{name}() outside serve/sessions.py — session "
                    f"state mutations (step tags, TTL, spill wiring) "
                    f"are the session manager's alone; route through "
                    f"SessionManager so devcache and arena stay "
                    f"consistent")


@register
class QidMintRule(Rule):
    """``new_query_id`` outside obs/ (unsampled tracing on hot
    paths)."""

    id = "qid-mint"
    rationale = ("hot paths mint through obs.sample_qid so tracing "
                 "cost follows config.obs_trace_sample")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith("netsdb_tpu/") \
            and not mod.rel.startswith(_OBS)

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            hit = False
            if isinstance(node, ast.Call):
                hit = terminal_name(node.func) == "new_query_id"
            elif isinstance(node, ast.ImportFrom):
                hit = any(a.name == "new_query_id" for a in node.names)
            if hit:
                yield self.diag(
                    mod, node,
                    "new_query_id outside obs/ — unsampled qid "
                    "minting pays full tracing per request; mint "
                    "through obs.sample_qid (config.obs_trace_sample)")
