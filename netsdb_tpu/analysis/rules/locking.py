"""Lock-ordering and holds-across-blocking-call rules — now
INTERPROCEDURAL over the project call graph.

The PR 8 versions of these rules saw lexical nesting plus same-module
call-through.  This rewrite stands them on
``analysis/callgraph.py`` + ``analysis/summaries.py``: a ``with`` in
``serve/server.py`` that calls into ``storage/devcache.py`` which
takes another tracked lock now contributes a lock-order edge naming
BOTH sites (the holding call site and the callee's acquisition line),
and a call chain that reaches ``recv``/``queue.get()``/``device_put``
while any caller up-stack holds a lock is flagged at the holding call
site — not just when the blocking call is lexically visible under the
``with``.

Rank tokens, not instances: every per-set serve lock is one rank
(``ServeController._set_locks[]``), every relation ``RWLock`` is one
rank PER OWNER CLASS (``PagedObjects.rw``, ``PagedColumns.rw``,
``_PagedMatrix.rw``) — lock *levels* order, instances don't.  Token
normalization lives in ``analysis/summaries.py`` and deliberately
matches the runtime witness rank strings, so the static graph and the
witness's dynamic graph diff cleanly (``cli lint
--witness-coverage``).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from netsdb_tpu.analysis.callgraph import fmt_key
from netsdb_tpu.analysis.lint import (Diagnostic, Project, Rule,
                                      register)
from netsdb_tpu.analysis.summaries import base_token, summaries

#: the seeded known hierarchy (audited in PR 8 — note the direction:
#: ``append_table`` nests append_mu -> store lock, and the ingest /
#: replace paths nest store lock -> relation RWLock; the PRE-PR-6
#: order (store lock held across PagedObjects.append) is exactly the
#: inversion this rule exists to catch)
SEED_EDGES: Tuple[Tuple[str, str], ...] = (
    ("_StoredSet.append_mu", "SetStore._lock"),
    # relation rw ranks are per owner class (fresh-ingest appends and
    # the paged-matmul read both run under the store lock)
    ("SetStore._lock", "PagedObjects.rw"),
    ("SetStore._lock", "PagedColumns.rw"),
    ("SetStore._lock", "_PagedMatrix.rw"),
    ("_StoredSet.append_mu", "PagedObjects.rw"),
    ("_StoredSet.append_mu", "PagedColumns.rw"),
    ("PagedObjects._append_mu", "PagedObjects.rw"),
    ("SetStore._lock", "DeviceBlockCache._mu"),
    ("SetStore._lock", "_PyPageBackend._mu"),
    # serve/server.py mirrored-frame ordering (audited: _run_mirrored
    # holds the per-set lock across _mirror_once, which takes
    # _mirror_lock then _followers_mu; SPMD topologies serialize the
    # whole thing under _collective_lock first)
    ("ServeController._collective_lock", "ServeController._mirror_lock"),
    ("ServeController._mirror_lock", "ServeController._followers_mu"),
    ("ServeController._set_locks_mu", "ServeController._set_locks[]"),
    ("ServeController._set_locks[]", "ServeController._mirror_lock"),
    # HA durability (ISSUE 16): the mirror path appends to the durable
    # mutation log INSIDE the mirror critical section (log order must
    # equal link FIFO order), and the shard pool spills its handoff
    # buffer to the same log class under its own mutex; the log's lock
    # is a strict leaf, so neither edge can close a cycle
    ("ServeController._mirror_lock", "storage.MutationLog._mu"),
    ("serve.ShardPool._mu", "storage.MutationLog._mu"),
)

#: modules that IMPLEMENT the primitives (their internals necessarily
#: wait under their own locks)
BLOCKING_EXEMPT = ("netsdb_tpu/utils/locks.py",)


class EdgeSite:
    """Where one lock-order edge was sighted in code."""

    __slots__ = ("rel", "line", "inner_rel", "inner_line", "via")

    def __init__(self, rel: str, line: int,
                 inner_rel: Optional[str] = None,
                 inner_line: Optional[int] = None,
                 via: Optional[str] = None):
        self.rel = rel
        self.line = line
        # for call-through edges: the callee acquisition site
        self.inner_rel = inner_rel
        self.inner_line = inner_line
        self.via = via  # callee key string, for the report

    def describe(self) -> str:
        s = f"{self.rel}:{self.line}"
        if self.inner_rel is not None:
            s += f" (acquired in {self.via} at " \
                 f"{self.inner_rel}:{self.inner_line})"
        return s


def static_lock_edges(project: Project
                      ) -> Dict[Tuple[str, str], Optional[EdgeSite]]:
    """The full static lock-order edge set: seeds (site None until a
    code sighting upgrades them), lexical nesting, and cross-module
    call-through edges derived from the transitive lock summaries.
    Shared by the lock-order rule and the witness-coverage report."""
    def build() -> Dict[Tuple[str, str], Optional[EdgeSite]]:
        S = summaries(project)
        edges: Dict[Tuple[str, str], Optional[EdgeSite]] = {
            e: None for e in SEED_EDGES}

        def note(key: Tuple[str, str], site: EdgeSite) -> None:
            # first CODE sighting wins; it also upgrades a seed's
            # None site so cycle reports name real file:line anchors
            if edges.get(key) is None:
                edges[key] = site

        # instance qualifiers (``C.mu@self._a``) are a RACE-rule
        # refinement; lock ORDER is about ranks, where every instance
        # of a class is one level — strip before edges so the graph
        # keeps matching the runtime witness rank grammar
        for key, facts in S.facts.items():
            for outer, inner, line in facts.lex_edges:
                outer, inner = base_token(outer), base_token(inner)
                if outer != inner:
                    note((outer, inner), EdgeSite(key[0], line))
            for site in facts.calls:
                if not site.held:
                    continue
                callee_locks = S.trans_locks.get(site.callee, {})
                for inner, (irel, iline) in callee_locks.items():
                    inner = base_token(inner)
                    if inner.startswith("*."):
                        continue
                    for outer in site.held:
                        outer = base_token(outer)
                        if inner != outer:
                            note((outer, inner),
                                 EdgeSite(key[0], site.line,
                                          inner_rel=irel,
                                          inner_line=iline,
                                          via=fmt_key(site.callee)))
        return edges

    return project.cached("static_lock_edges", build)


@register
class LockOrderRule(Rule):
    """Cross-module lock-acquisition-order cycles (potential AB/BA
    deadlocks), from lexical nesting + call-graph call-through +
    the seeded hierarchy."""

    id = "lock-order"
    rationale = ("a cycle in the with-lock nesting graph is a potential "
                 "deadlock even if no test ever interleaves it")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        edges = static_lock_edges(project)
        # wildcard tokens never join the graph (ambiguous owners would
        # manufacture cycles out of coincidental attribute names)
        graph: Dict[str, Set[str]] = {}
        for (a, b), _site in edges.items():
            if a.startswith("*.") or b.startswith("*."):
                continue
            graph.setdefault(a, set()).add(b)
        for cycle in _find_cycles(graph):
            # anchor the report at the first code-sighted edge in the
            # cycle (a pure-seed cycle anchors at line 1 of this file)
            anchor = None
            for i in range(len(cycle)):
                e = (cycle[i], cycle[(i + 1) % len(cycle)])
                if edges.get(e) is not None:
                    anchor = edges[e]
                    break
            path, line = (anchor.rel, anchor.line) if anchor \
                else ("netsdb_tpu", 1)
            chain = " -> ".join(cycle + [cycle[0]])
            sites = "; ".join(
                f"{a}->{b} at {edges[(a, b)].describe()}"
                for a, b in zip(cycle, cycle[1:] + [cycle[0]])
                if edges.get((a, b)) is not None) or "seeded edges only"
            yield Diagnostic(
                rule=self.id, path=path, line=line, col=0,
                message=f"lock-order cycle {chain} ({sites}) — a "
                        f"thread taking these in one order can "
                        f"deadlock a thread taking the other")


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS back-edges; each cycle reported once
    (canonical rotation)."""
    seen: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]):
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                i = stack.index(nxt)
                cycle = stack[i:]
                k = cycle.index(min(cycle))
                canon = tuple(cycle[k:] + cycle[:k])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return out


@register
class LockBlockingCallRule(Rule):
    """Blocking calls (socket recv/accept, device_put, queue.get
    without timeout, seeded patterns) reached while a lock is held —
    lexically under the ``with``, or through any resolved call chain
    (the interprocedural extension)."""

    id = "lock-blocking-call"
    rationale = ("a blocking call under a lock turns one slow peer "
                 "into a whole-subsystem stall")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        S = summaries(project)
        for key, facts in S.facts.items():
            rel = key[0]
            if rel in BLOCKING_EXEMPT:
                continue
            # lexical: a blocking call textually under the with
            for what, line, held in facts.blocking:
                if not held:
                    continue
                yield Diagnostic(
                    rule=self.id, path=rel, line=line, col=0,
                    message=f"blocking call {what} while holding "
                            f"{', '.join(held)} — a slow peer stalls "
                            f"every waiter on the lock; move the "
                            f"wait outside or bound it")
            # interprocedural: a locked call site whose callee
            # transitively reaches a blocking call
            reported: Set[Tuple[int, str]] = set()
            for site in facts.calls:
                if not site.held:
                    continue
                blk = S.trans_blocking.get(site.callee, {})
                for what, (brel, bline, depth) in sorted(blk.items()):
                    if brel in BLOCKING_EXEMPT:
                        continue
                    if (site.line, what) in reported:
                        continue
                    reported.add((site.line, what))
                    hops = f"{depth + 1} call hop" \
                           f"{'s' if depth else ''} down"
                    yield Diagnostic(
                        rule=self.id, path=rel, line=site.line, col=0,
                        message=f"call into {fmt_key(site.callee)} "
                                f"reaches blocking {what} at "
                                f"{brel}:{bline} ({hops}) while "
                                f"holding {', '.join(site.held)} — "
                                f"a slow peer stalls every waiter on "
                                f"the lock; move the wait outside "
                                f"the lock or bound it")
