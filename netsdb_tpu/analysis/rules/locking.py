"""Lock-ordering and holds-across-blocking-call rules.

The pass the regex scanners could never do: extract every ``with
<lock>:`` statement, normalize the lock expression to a *rank token*
(which class/module owns it), build the nesting graph — lexical
nesting plus same-module call-through — union it with the seeded
known hierarchy, and fail on any cycle.  A cycle in this graph is a
potential AB/BA deadlock that may never have fired in a test; the
runtime twin (``utils/locks.LockWitness``) catches the orders that
only materialize dynamically.

Rank tokens, not instances: every per-set serve lock is one rank
(``ServeController._set_locks[]``), every relation ``RWLock`` is one
rank PER OWNER CLASS (``PagedObjects.rw``, ``PagedColumns.rw``,
``_PagedMatrix.rw``) — lock *levels* order, instances don't, and
collapsing distinct rw families would mix their usage modes.

Token normalization:

* ``self.X`` inside class ``C`` → ``C.X``;
* module-level ``X`` in module ``m.py`` → ``m.py:X``;
* ``other.X`` (attribute on a non-self base) → resolved through the
  project-wide *lock attribute index* (which classes assign a lock to
  ``self.X``): a unique owner gives ``C.X``; an ambiguous name stays
  the wildcard ``*.X`` and contributes NO cross-class edges (no false
  cycles from coincidental attribute names);
* ``base.rw.read()`` / ``.write()`` → the shared ``RWLock`` rank (the
  storage layer's leaf — many relations, one level);
* a local alias (``lk = self._set_lock(db, s)``; ``with lk:``)
  resolves to the aliased expression's token.

The blocking rule flags calls that can wait on another thread or on
I/O made while a lock is lexically held: socket ``recv``/``accept``,
``device_put`` (a host→device copy on the consumer's critical path),
``queue.get()`` without a timeout, and the seeded site-specific
patterns (``po.append`` — a ``PagedObjects`` append waits on the
relation's stream locks).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from netsdb_tpu.analysis.lint import (Diagnostic, Module, Project, Rule,
                                      enclosing_functions, register,
                                      terminal_name)

#: terminal names that denote a lock when used as ``with <expr>:``
_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|lk|mu|mutex)$|_mu$|_lock$|^mu$|^lock$")

#: constructor call names whose assignment marks ``self.X`` as a lock
_LOCK_CTORS = {"Lock", "RLock", "RWLock", "TrackedLock", "TrackedRLock",
               "witness_lock"}

#: the seeded known hierarchy (audited this PR — note the direction:
#: ``append_table`` nests append_mu -> store lock, and the ingest /
#: replace paths nest store lock -> relation RWLock; the PRE-PR-6
#: order (store lock held across PagedObjects.append) is exactly the
#: inversion this rule exists to catch)
SEED_EDGES: Tuple[Tuple[str, str], ...] = (
    ("_StoredSet.append_mu", "SetStore._lock"),
    # relation rw ranks are per owner class (fresh-ingest appends and
    # the paged-matmul read both run under the store lock)
    ("SetStore._lock", "PagedObjects.rw"),
    ("SetStore._lock", "PagedColumns.rw"),
    ("SetStore._lock", "_PagedMatrix.rw"),
    ("_StoredSet.append_mu", "PagedObjects.rw"),
    ("_StoredSet.append_mu", "PagedColumns.rw"),
    ("PagedObjects._append_mu", "PagedObjects.rw"),
    ("SetStore._lock", "DeviceBlockCache._mu"),
    ("SetStore._lock", "_PyPageBackend._mu"),
    # serve/server.py mirrored-frame ordering (audited: _run_mirrored
    # holds the per-set lock across _mirror_once, which takes
    # _mirror_lock then _followers_mu; SPMD topologies serialize the
    # whole thing under _collective_lock first)
    ("ServeController._collective_lock", "ServeController._mirror_lock"),
    ("ServeController._mirror_lock", "ServeController._followers_mu"),
    ("ServeController._set_locks_mu", "ServeController._set_locks[]"),
    ("ServeController._set_locks[]", "ServeController._mirror_lock"),
)

#: method names that block on I/O or another thread
_BLOCKING_METHODS = {"recv", "recv_into", "recvmsg", "accept",
                     "device_put"}
#: seeded site-specific blocking patterns: (receiver terminal, method)
_BLOCKING_SEEDED = {("po", "append")}
#: receiver terminal names treated as queues for the .get() check
_QUEUE_RECV_RE = re.compile(r"(^|_)q(ueue)?s?$|queue")

#: modules that IMPLEMENT the primitives (their internals necessarily
#: wait under their own locks)
_BLOCKING_EXEMPT = ("netsdb_tpu/utils/locks.py",)


def _is_lock_name(name: Optional[str]) -> bool:
    return bool(name) and bool(_LOCK_NAME_RE.search(name))


def _lock_attr_index(project: Project) -> Dict[str, Set[str]]:
    """attr name → set of class names assigning a lock to ``self.X``
    (constructor calls and ``dataclasses.field(default_factory=
    threading.Lock)`` defaults)."""
    def build() -> Dict[str, Set[str]]:
        idx: Dict[str, Set[str]] = {}
        for mod in project.modules:
            if mod.tree is None:
                continue
            for cls_name, fn in mod.functions():
                if cls_name is None:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not _assigns_lock(node.value):
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            idx.setdefault(t.attr, set()).add(cls_name)
            # dataclass fields: append_mu: Any = field(
            #     default_factory=threading.Lock)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is not None \
                            and isinstance(stmt.target, ast.Name) \
                            and _field_factory_is_lock(stmt.value):
                        idx.setdefault(stmt.target.id,
                                       set()).add(node.name)
        return idx

    return project.cached("lock_attr_index", build)


def _assigns_lock(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        t = terminal_name(value.func)
        if t in _LOCK_CTORS:
            return True
        return _field_factory_is_lock(value)
    return False


def _field_factory_is_lock(value: ast.AST) -> bool:
    if not (isinstance(value, ast.Call)
            and terminal_name(value.func) == "field"):
        return False
    for kw in value.keywords:
        if kw.arg != "default_factory":
            continue
        target = kw.value
        # field(default_factory=lambda: TrackedLock("rank"))
        if isinstance(target, ast.Lambda) \
                and isinstance(target.body, ast.Call):
            target = target.body.func
        if terminal_name(target) in _LOCK_CTORS:
            return True
    return False


class _FnLocks:
    """Per-function lock facts: tokens acquired lexically, plus the
    ``with``-nesting edges found inside."""

    def __init__(self):
        self.acquired: Set[str] = set()
        # (outer, inner, line) lexical nesting edges
        self.edges: List[Tuple[str, str, int]] = []
        # (held_token, callee_key, line) same-module call-through;
        # callee_key = (class_or_None, name) so same-named methods on
        # DIFFERENT classes cannot collide
        self.calls_under: List[Tuple[str, Tuple[Optional[str], str],
                                     int]] = []


def _local_aliases(fn: ast.AST) -> Dict[str, ast.AST]:
    """name → RHS for single-target simple assignments in ``fn`` —
    the one-hop alias resolver (``lk = self._set_lock(...)``)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, (ast.Attribute, ast.Call)):
            name = node.targets[0].id
            # a name assigned twice is not a stable alias
            out[name] = None if name in out else node.value
    return {k: v for k, v in out.items() if v is not None}


def _lock_token(expr: ast.AST, cls: Optional[str], mod: Module,
                aliases: Dict[str, ast.AST],
                attr_index: Dict[str, Set[str]],
                _depth: int = 0) -> Optional[str]:
    """Normalize a ``with`` context expression to a rank token, or
    None when it doesn't look like a lock."""
    if _depth > 3:
        return None
    # rw.read() / rw.write() → the owner class's rw rank (each
    # relation class is its own lock level; collapsing them all into
    # one "RWLock" rank mixes read-only and write-append usage of
    # DIFFERENT lock families and manufactures cycles)
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
        if expr.func.attr in ("read", "write"):
            base = expr.func.value
            bt = terminal_name(base)
            if not (bt == "rw" or (bt or "").endswith("rw")):
                return None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls:
                return f"{cls}.rw"
            owners = attr_index.get("rw", set())
            if len(owners) == 1:
                return f"{next(iter(owners))}.rw"
            return "*.rw"  # ambiguous owner: contributes no edges
        # self._set_lock(db, s) style: a method returning a lock
        if _is_lock_name(expr.func.attr) or expr.func.attr.endswith(
                ("_lock", "_mu")):
            owner = None
            if isinstance(expr.func.value, ast.Name) \
                    and expr.func.value.id == "self" and cls:
                owner = cls
            name = expr.func.attr
            # the per-set-lock idiom: a getter named _set_lock maps to
            # the instance-family rank C._set_locks[]
            if name.startswith("_set_lock"):
                return f"{owner or '*'}._set_locks[]"
            return f"{owner or '*'}.{name}()"
        return None
    if isinstance(expr, ast.Call):  # Lock() inline — anonymous, skip
        return None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
        if not _is_lock_name(name):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            return f"{cls}.{name}"
        owners = attr_index.get(name, set())
        if len(owners) == 1:
            return f"{next(iter(owners))}.{name}"
        return f"*.{name}"
    if isinstance(expr, ast.Name):
        if expr.id in aliases:
            return _lock_token(aliases[expr.id], cls, mod, aliases,
                               attr_index, _depth + 1)
        if _is_lock_name(expr.id):
            return f"{mod.rel}:{expr.id}"
        return None
    return None


def _collect_fn_locks(mod: Module, cls: Optional[str], fn: ast.AST,
                      attr_index: Dict[str, Set[str]]) -> _FnLocks:
    facts = _FnLocks()
    aliases = _local_aliases(fn)

    def tok(expr: ast.AST) -> Optional[str]:
        return _lock_token(expr, cls, mod, aliases, attr_index)

    def visit(node: ast.AST, held: List[Tuple[str, int]]):
        if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            return  # nested defs get their own pass (own alias scope)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                visit(item.context_expr, held)  # evaluated under OUTER
                t = tok(item.context_expr)
                if t is None:
                    continue
                facts.acquired.add(t)
                for outer, _line in new_held:
                    if outer != t:  # re-entrant same-rank: no edge
                        facts.edges.append(
                            (outer, t, item.context_expr.lineno))
                new_held.append((t, item.context_expr.lineno))
            for sub in node.body:
                visit(sub, new_held)
            return
        if held and isinstance(node, ast.Call):
            callee = _same_module_callee(node, cls)
            if callee is not None:
                for outer, _line in held:
                    facts.calls_under.append(
                        (outer, callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(fn, [])
    return facts


def _same_module_callee(call: ast.Call, cls: Optional[str]
                        ) -> Optional[Tuple[Optional[str], str]]:
    """``self.m(...)`` → ``(enclosing_class, m)``; bare ``f(...)`` →
    ``(None, f)``; else None."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return (cls, f.attr)
    if isinstance(f, ast.Name):
        return (None, f.id)
    return None


@register
class LockOrderRule(Rule):
    """Cross-module lock-acquisition-order cycles (potential AB/BA
    deadlocks), from lexical nesting + same-module call-through +
    the seeded hierarchy."""

    id = "lock-order"
    rationale = ("a cycle in the with-lock nesting graph is a potential "
                 "deadlock even if no test ever interleaves it")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        attr_index = _lock_attr_index(project)
        # edge → (path, line) of first sighting; seeds carry none
        edges: Dict[Tuple[str, str], Optional[Tuple[str, int]]] = {
            e: None for e in SEED_EDGES}
        def note_edge(key: Tuple[str, str], site: Tuple[str, int]):
            # first CODE sighting wins; it also upgrades a seed's
            # None site so cycle reports name real file:line anchors
            if edges.get(key) is None:
                edges[key] = site

        for mod in project.modules:
            if mod.tree is None:
                continue
            # keyed (class, name): same-named methods on different
            # classes in one module must not collide
            fn_facts: Dict[Tuple[Optional[str], str], _FnLocks] = {}
            ordered: List[Tuple[_FnLocks, Module]] = []
            for cls, fn in mod.functions():
                facts = _collect_fn_locks(mod, cls, fn, attr_index)
                fn_facts[(cls, fn.name)] = facts
                ordered.append((facts, mod))
            # transitive acquires through same-module calls (bounded)
            for _ in range(3):
                changed = False
                for facts, _m in ordered:
                    for _outer, callee, _line in facts.calls_under:
                        callee_facts = fn_facts.get(callee)
                        if callee_facts and not (
                                callee_facts.acquired
                                <= facts.acquired):
                            facts.acquired |= callee_facts.acquired
                            changed = True
                if not changed:
                    break
            for facts, m in ordered:
                for outer, inner, line in facts.edges:
                    note_edge((outer, inner), (m.rel, line))
                for outer, callee, line in facts.calls_under:
                    callee_facts = fn_facts.get(callee)
                    if not callee_facts:
                        continue
                    for inner in callee_facts.acquired:
                        if inner != outer and not inner.startswith("*."):
                            note_edge((outer, inner), (m.rel, line))
        # wildcard tokens never join the graph (ambiguous owners would
        # manufacture cycles out of coincidental attribute names)
        graph: Dict[str, Set[str]] = {}
        for (a, b), _site in edges.items():
            if a.startswith("*.") or b.startswith("*."):
                continue
            graph.setdefault(a, set()).add(b)
        for cycle in _find_cycles(graph):
            # anchor the report at the first code-sighted edge in the
            # cycle (a pure-seed cycle anchors at line 1 of this file)
            anchor = None
            for i in range(len(cycle)):
                e = (cycle[i], cycle[(i + 1) % len(cycle)])
                if edges.get(e) is not None:
                    anchor = edges[e]
                    break
            path, line = anchor if anchor else ("netsdb_tpu", 1)
            chain = " -> ".join(cycle + [cycle[0]])
            sites = "; ".join(
                f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cycle, cycle[1:] + [cycle[0]])
                if edges.get((a, b)) is not None) or "seeded edges only"
            yield Diagnostic(
                rule=self.id, path=path, line=line, col=0,
                message=f"lock-order cycle {chain} ({sites}) — a "
                        f"thread taking these in one order can "
                        f"deadlock a thread taking the other")


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS back-edges; each cycle reported once
    (canonical rotation)."""
    seen: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]):
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                i = stack.index(nxt)
                cycle = stack[i:]
                k = cycle.index(min(cycle))
                canon = tuple(cycle[k:] + cycle[:k])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return out


@register
class LockBlockingCallRule(Rule):
    """Blocking calls (socket recv/accept, device_put, queue.get
    without timeout, seeded patterns) made while a lock is lexically
    held — the stall-the-world shape of the PR 6 inversion."""

    id = "lock-blocking-call"
    rationale = ("a blocking call under a lock turns one slow peer "
                 "into a whole-subsystem stall")

    def select(self, mod: Module) -> bool:
        return mod.rel not in _BLOCKING_EXEMPT

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        attr_index: Dict[str, Set[str]] = {}
        for cls, fn in mod.functions():
            aliases = _local_aliases(fn)
            yield from self._check_fn(mod, cls, fn, aliases, attr_index)

    def _check_fn(self, mod: Module, cls, fn, aliases, attr_index):
        def tok(expr):
            return _lock_token(expr, cls, mod, aliases, attr_index)

        def walk_with(node, held: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    toks = [t for t in (tok(i.context_expr)
                                        for i in child.items)
                            if t is not None]
                    for sub in child.body:
                        yield from walk_with(sub, held + toks)
                    # with-item expressions themselves checked under
                    # the OUTER held set
                    for i in child.items:
                        yield from walk_with(i, held)
                    continue
                if held and isinstance(child, ast.Call):
                    d = self._blocking(mod, child, held)
                    if d is not None:
                        yield d
                yield from walk_with(child, held)

        yield from walk_with(fn, [])

    def _blocking(self, mod: Module, call: ast.Call,
                  held: List[str]) -> Optional[Diagnostic]:
        f = call.func
        name = terminal_name(f)
        if name is None:
            return None
        recv = terminal_name(f.value) if isinstance(f, ast.Attribute) \
            else None
        what = None
        if name in _BLOCKING_METHODS:
            what = f"{name}()"
        elif recv is not None and (recv, name) in _BLOCKING_SEEDED:
            what = f"{recv}.{name}() (PagedObjects.append waits on "\
                   f"the relation's stream locks)"
        elif name == "get" and recv is not None \
                and _QUEUE_RECV_RE.search(recv):
            kws = {kw.arg for kw in call.keywords}
            nonblocking = "timeout" in kws or any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in call.keywords) \
                or len(call.args) >= 2 \
                or (len(call.args) == 1 and isinstance(
                    call.args[0], ast.Constant)
                    and call.args[0].value is False)
            if not nonblocking:
                what = f"{recv}.get() without a timeout"
        if what is None:
            return None
        return self.diag(
            mod, call,
            f"blocking call {what} while holding "
            f"{', '.join(held)} — a slow peer stalls every waiter on "
            f"the lock; move the wait outside or bound it")
