"""Static shared-state race rule over thread-root reachability.

The worker runtime is threads over shared objects (staging workers,
trace shippers, follower mirrors, scheduler installers, telemetry
daemons).  The lock rules catch *misordered* locking; this rule
catches *missing* locking: an attribute of a shared object mutated
from two different concurrent entry points where at least one
mutating path holds no tracked lock covering the owner class.

Semantics (``docs/ANALYSIS.md`` — "Interprocedural analysis"):

* **Shared classes** — the audited hierarchy's owner classes
  (:data:`SHARED_SEED`, the classes whose rank tokens appear in the
  lock table) plus any class that assigns a tracked/threading lock to
  ``self`` (owning a lock is a declaration that instances are
  shared).
* **Mutation** — ``self.X = / += / self.X[k] =`` in any method other
  than construction (``__init__``/``__post_init__``), where ``X`` is
  not itself a lock attribute.
* **Thread roots** — resolved ``threading.Thread(target=...)`` /
  executor ``submit(...)`` entry points from the call graph.
* **Covering lock** — a rank token whose owner-class prefix is the
  mutated object's class (``SetStore._lock`` covers ``SetStore``).
  Coverage is path-sensitive: a root's path into the mutating method
  is *covered* when some call site along it (or the mutation site
  itself) holds a covering token.

A finding fires when ≥ 2 distinct thread roots reach mutations of one
``Class.attr`` AND at least one root reaches a mutation over a fully
uncovered path.  Single-threaded mutation (construction, test-only
use) never fires; a lock-protected twin of a racy class never fires —
both shapes are pinned by fixtures.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from netsdb_tpu.analysis.callgraph import FuncKey, fmt_key
from netsdb_tpu.analysis.lint import (Diagnostic, Project, Rule,
                                      register, set_gauge)
from netsdb_tpu.analysis.summaries import (Summaries, base_token,
                                           is_lock_name, summaries,
                                           token_owner,
                                           token_qualifier)

#: owner classes of the audited lock hierarchy (docs/ANALYSIS.md) —
#: instances of these are shared across threads BY DESIGN, so every
#: unlocked mutation path is suspect
SHARED_SEED = (
    "SetStore", "_StoredSet", "PagedObjects", "PagedColumns",
    "_PagedMatrix", "DeviceBlockCache", "_PyPageBackend",
    "PagedTensorStore", "ServeController", "_FollowerLink",
    "_IdempotencyCache", "RemoteClient", "ChaosInjector",
    "LaneScheduler", "CoalesceTable", "AffinityGate",
    "TraceRing", "ResourceLedger", "SlowQueryLog",
    "TelemetryHistory", "SLOEngine", "OperatorLedger",
)

#: methods that are construction / teardown, not concurrent mutation
_CONSTRUCTION = {"__init__", "__post_init__", "__new__",
                 "__init_subclass__"}


def _covers(token: str, cls: str,
            receiver: Optional[str]) -> bool:
    """Does a held ``token`` cover class ``cls`` at a call site whose
    receiver path is ``receiver``?  Unqualified ranks cover the whole
    class; an instance-qualified rank (``C.mu@self._a``) covers only
    calls dispatched on that same instance path (or a member of it —
    ``self._a.inner.step()`` stays under ``self._a``'s lock)."""
    if token_owner(base_token(token)) != cls:
        return False
    qual = token_qualifier(token)
    if qual is None:
        return True
    return receiver is not None and (
        receiver == qual or receiver.startswith(qual + "."))


def _reach(S: Summaries, root: FuncKey,
           uncovered_for: Optional[str] = None) -> Set[FuncKey]:
    """Call-graph reachability from ``root`` with the CONSTRUCTION
    BARRIER (an object still inside ``__init__`` is thread-local, so
    its helpers' writes are not shared-state mutations — paths never
    continue through construction methods).

    With ``uncovered_for=C``, additionally prune every call site
    holding a lock token covering owner class ``C`` — the callee runs
    entirely inside the ``with``, so the whole subtree below a
    covered site is covered. The result is then the set of functions
    some path reaches with NO covering lock held.

    Coverage is INSTANCE-SENSITIVE for member-object locks: a token
    qualified ``C.mu@self._a`` only covers a call whose receiver is
    that same instance path (``self._a.step()``) — holding
    ``self._a.mu`` says nothing about the ``C`` instance behind
    ``self._b``. Unqualified tokens (``C.mu`` from ``with self.mu:``)
    keep their class-wide coverage."""
    seen: Set[FuncKey] = {root}
    stack = [root]
    while stack:
        cur = stack.pop()
        if cur[2] in _CONSTRUCTION and cur != root:
            continue
        facts = S.facts.get(cur)
        if facts is None:
            continue
        for site in facts.calls:
            if uncovered_for is not None and any(
                    _covers(t, uncovered_for, site.receiver)
                    for t in site.held):
                continue
            if site.callee not in seen:
                seen.add(site.callee)
                stack.append(site.callee)
    return seen


@register
class SharedStateRaceRule(Rule):
    """Attributes of shared objects mutated from ≥2 thread roots with
    at least one uncovered mutating path."""

    id = "shared-state-race"
    rationale = ("state mutated from two thread roots with no "
                 "covering lock on some path is a data race waiting "
                 "for the scheduler to expose it")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        S = summaries(project)
        G = S.graph
        shared: Set[str] = set(SHARED_SEED)
        for owners in S.attr_index.values():
            shared |= owners
        lock_attrs: Dict[str, Set[str]] = {}
        for attr, owners in S.attr_index.items():
            for cls in owners:
                lock_attrs.setdefault(cls, set()).add(attr)

        # (class, attr) → [(FuncKey, line, held)]
        sites: Dict[Tuple[str, str],
                    List[Tuple[FuncKey, int, Tuple[str, ...]]]] = {}
        for key, facts in S.facts.items():
            cls = key[1]
            if cls is None or cls not in shared \
                    or key[2] in _CONSTRUCTION:
                continue
            for attr, line, held in facts.mutations:
                if attr in lock_attrs.get(cls, ()) \
                        or is_lock_name(attr):
                    continue
                sites.setdefault((cls, attr), []).append(
                    (key, line, held))

        findings = 0
        #: (root, owner) → uncovered reachability, computed lazily
        unc_cache: Dict[Tuple[FuncKey, str], Set[FuncKey]] = {}
        #: root → construction-barrier reachability, computed lazily
        reach_cache: Dict[FuncKey, Set[FuncKey]] = {}

        def reach(root: FuncKey) -> Set[FuncKey]:
            if root not in reach_cache:
                reach_cache[root] = _reach(S, root)
            return reach_cache[root]

        for (cls, attr), muts in sorted(sites.items()):
            methods = {key for key, _line, _held in muts}
            roots = [r for r in G.thread_roots.values()
                     if any(m in reach(r.key) for m in methods)]
            if len(roots) < 2:
                continue
            for key, line, held in muts:
                # the mutated object is always ``self``, so a member-
                # object lock (``C.mu@self._a``) guards a DIFFERENT
                # instance and never covers the site
                if any(token_qualifier(t) is None
                       and token_owner(t) == cls for t in held):
                    continue  # the mutation site itself is covered
                bad_roots = []
                for r in roots:
                    ck = (r.key, cls)
                    if ck not in unc_cache:
                        unc_cache[ck] = _reach(S, r.key,
                                               uncovered_for=cls)
                    if key in unc_cache[ck]:
                        bad_roots.append(r)
                if not bad_roots:
                    continue
                mod = project.module(key[0])
                if mod is not None and mod.suppressed(self.id, line):
                    # inline-suppressed (documented reason): run_lint
                    # would drop it anyway — keep the exported gauge
                    # agreeing with what lint actually reports
                    continue
                findings += 1
                root_names = ", ".join(sorted(
                    fmt_key(r.key) for r in roots))
                spawn = bad_roots[0].sites[0] \
                    if bad_roots[0].sites else ("?", 0)
                yield Diagnostic(
                    rule=self.id, path=key[0], line=line, col=0,
                    message=f"{cls}.{attr} is mutated here with no "
                            f"{cls} lock held, yet it is reachable "
                            f"from {len(roots)} thread roots "
                            f"({root_names}) — e.g. the root spawned "
                            f"at {spawn[0]}:{spawn[1]} reaches this "
                            f"mutation over a lock-free path; guard "
                            f"the write or document why it is safe")
        set_gauge("analysis.race_findings", findings)
