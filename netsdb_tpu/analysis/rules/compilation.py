"""Plan-compilation discipline: who may mint compiled programs.

PR 18 made fusion regions the unit scatter-gather ships and merges —
every compiled program for a scatter subplan must be born on the
region path (``plan/fusion.py``: the region executor's keys, or
``compile_scatter_merge`` for the coordinator's merge+finalize). A
serve-layer module reaching for ``plan/executor._cached_jit`` directly
would mint a program the region tree never shows, the rollback arms
(``plan_fusion=off`` / ``fusion_mapper=greedy``) never disable, and
the ``fusion.distributed_regions`` counter never counts.
"""

from __future__ import annotations

import ast
from typing import Iterable

from netsdb_tpu.analysis.lint import (Diagnostic, Module, Rule,
                                      register, terminal_name)

_SERVE = "netsdb_tpu/serve/"
_SCATTER = "netsdb_tpu/plan/scatter.py"


@register
class ScatterJitRule(Rule):
    """Any ``_cached_jit`` mention on the scatter paths (serve/ and
    plan/scatter.py) — compiled scatter programs are minted only by
    ``plan/fusion.py``'s region path."""

    id = "scatter-jit-route"
    rationale = ("scatter subplan/merge programs compile through "
                 "plan/fusion.py's region path (compile_scatter_merge "
                 "/ the region executor) or they escape the region "
                 "tree, the fusion rollback arms and the "
                 "fusion.distributed_regions count")

    def select(self, mod: Module) -> bool:
        return mod.rel.startswith(_SERVE) or mod.rel == _SCATTER

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for node in mod.walk():
            hit = None
            if isinstance(node, ast.Call):
                if terminal_name(node.func) == "_cached_jit":
                    hit = "call"
            elif isinstance(node, ast.ImportFrom):
                if any(a.name == "_cached_jit" for a in node.names):
                    hit = "import"
            if hit:
                yield self.diag(
                    mod, node,
                    f"direct _cached_jit ({hit}) on a scatter path — "
                    f"compile scatter programs through plan/fusion.py "
                    f"(compile_scatter_merge, or let the shard's own "
                    f"region executor compile the pushed subplan) so "
                    f"every distributed program is a region the "
                    f"EXPLAIN tree shows and plan_fusion=off rolls "
                    f"back")
