"""Rule modules self-register on import (``analysis.lint.register``).

Importing this package loads every rule; ``lint.all_rules()`` does it
lazily so the framework core stays import-cheap.
"""

from netsdb_tpu.analysis.rules import compilation  # noqa: F401
from netsdb_tpu.analysis.rules import discipline  # noqa: F401
from netsdb_tpu.analysis.rules import drift  # noqa: F401
from netsdb_tpu.analysis.rules import locking  # noqa: F401
from netsdb_tpu.analysis.rules import races  # noqa: F401
from netsdb_tpu.analysis.rules import resources  # noqa: F401
