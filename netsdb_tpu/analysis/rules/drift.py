"""Two-way code ↔ catalog ↔ docs drift rules.

``metrics-drift`` is the framework port of the PR 7 gate: every metric
name minted in code must be catalogued (``obs/export.CATALOG``) and
documented (``docs/METRICS.md``), and vice versa — the OpenMetrics
scrape surface can never silently diverge from the docs.

``analysis-docs-drift`` applies the same pattern to THIS subsystem:
every registered lint rule id must have a row in ``docs/ANALYSIS.md``
(id, rationale, example, suppression) and every documented row must
name a live rule — the rule catalog humans read is the rule set CI
runs, by construction.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Set, Tuple

from netsdb_tpu.analysis.lint import (Diagnostic, Project, Rule,
                                      register)

_INSTRUMENT_METHODS = {"counter", "gauge", "histogram"}


def _doc_table_names(path: str) -> Set[str]:
    """Backticked names in the first column of a markdown table."""
    out: Set[str] = set()
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                m = re.match(r"^\|\s*`([^`]+)`", line)
                if m:
                    out.add(m.group(1))
    except OSError:
        pass
    return out


@register
class MetricsCatalogRule(Rule):
    """Metric names: code ↔ obs/export.CATALOG ↔ docs/METRICS.md."""

    id = "metrics-drift"
    rationale = ("an uncatalogued metric is silently skipped by the "
                 "scrape; a stale doc row lies to operators")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        from netsdb_tpu.obs.export import CATALOG

        minted: Set[str] = set()
        prefixes: Set[str] = set()
        anchor = ("netsdb_tpu/obs/export.py", 1)
        for mod in project.modules:
            if mod.tree is None \
                    or not mod.rel.startswith("netsdb_tpu/"):
                continue
            for node in mod.walk():
                if not (isinstance(node, ast.Call) and node.args
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _INSTRUMENT_METHODS):
                    continue
                arg = node.args[0]
                consts = []
                if isinstance(arg, ast.Constant):
                    consts = [arg]
                elif isinstance(arg, ast.IfExp):
                    consts = [b for b in (arg.body, arg.orelse)
                              if isinstance(b, ast.Constant)]
                elif isinstance(arg, ast.JoinedStr) and arg.values \
                        and isinstance(arg.values[0], ast.Constant):
                    prefixes.add(str(arg.values[0].value))
                    continue
                for c in consts:
                    if isinstance(c.value, str):
                        minted.add(c.value)
        documented = _doc_table_names(
            os.path.join(project.repo, "docs", "METRICS.md"))

        def d(message: str) -> Diagnostic:
            return Diagnostic(rule=self.id, path=anchor[0],
                              line=anchor[1], col=0, message=message)

        for name in sorted(minted - set(CATALOG)):
            yield d(f"metric {name!r} is minted in code but missing "
                    f"from obs/export.CATALOG — the OpenMetrics "
                    f"scrape would silently skip it")
        for prefix in sorted(prefixes):
            if not any(k.startswith(prefix) for k in CATALOG):
                yield d(f"f-string metric family {prefix!r}* has no "
                        f"catalogued member in obs/export.CATALOG")
        for name in sorted(set(CATALOG) - documented):
            yield d(f"metric {name!r} is in obs/export.CATALOG but "
                    f"not documented in docs/METRICS.md")
        for name in sorted(documented - set(CATALOG)):
            yield d(f"metric {name!r} is documented in docs/METRICS.md "
                    f"but absent from obs/export.CATALOG (stale docs "
                    f"or a missing catalog entry)")


@register
class AnalysisDocsRule(Rule):
    """Lint rule ids: registry ↔ docs/ANALYSIS.md, both directions."""

    id = "analysis-docs-drift"
    rationale = ("the rule catalog humans read must be the rule set "
                 "CI runs — in both directions")

    def check_project(self, project: Project) -> Iterable[Diagnostic]:
        from netsdb_tpu.analysis.lint import (BAD_SUPPRESSION,
                                              PARSE_ERROR,
                                              STALE_BASELINE,
                                              UNUSED_SUPPRESSION,
                                              rule_ids)

        doc_path = os.path.join(project.repo, "docs", "ANALYSIS.md")
        documented = _doc_table_names(doc_path)
        registered = set(rule_ids()) | {BAD_SUPPRESSION,
                                        UNUSED_SUPPRESSION, PARSE_ERROR,
                                        STALE_BASELINE}
        anchor = "netsdb_tpu/analysis/lint.py"

        def d(message: str) -> Diagnostic:
            return Diagnostic(rule=self.id, path=anchor, line=1, col=0,
                              message=message)

        if not documented:
            yield d("docs/ANALYSIS.md is missing or has no rule "
                    "catalog table — every rule needs a documented "
                    "row (id, rationale, example, suppression)")
            return
        for rid in sorted(registered - documented):
            yield d(f"rule {rid!r} is registered but has no row in "
                    f"docs/ANALYSIS.md — document its rationale, "
                    f"example and suppression syntax")
        for rid in sorted(documented - registered):
            yield d(f"docs/ANALYSIS.md documents rule {rid!r} which "
                    f"is not registered — stale row or a missing "
                    f"rule module")
