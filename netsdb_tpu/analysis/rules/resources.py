"""Resource-discipline rule: closable stream iterators must be closed.

``PagedColumns.stream``/``stream_tables``, ``PagedObjects`` record
streams, ``PagedTensorStore.stream_blocks`` and ``stage_stream`` all
hold a relation READ LOCK (and, for staged streams, a background
upload thread) for the iterator's lifetime.  A consumer that abandons
one mid-way without ``close()`` leaves the lock to the garbage
collector — a concurrent ``drop``/append then waits on GC timing, the
exact class of stall the staging leak registry exists to catch at
runtime.  This rule catches it at lint time.

What counts as consumed correctly:

* ``with contextlib.closing(x.stream()) as it:`` / any ``with`` over
  the producer call;
* assignment whose variable is later ``.close()``d or wrapped in
  ``closing(...)``;
* passing the producer call directly to another call (ownership
  transfers — ``stage_stream(self._host_stream(), ...)``);
* ``return``/``yield from`` of the producer call (the caller owns it);
* comprehensions (they drain to exhaustion; a generator that raises
  mid-drain propagates — acceptable).

What gets flagged:

* ``for chunk in x.stream():`` — a statement-for directly over the
  producer: a ``break``, ``return``, exception, or (inside a
  generator) an abandoned outer iterator leaks the read lock;
* ``x = y.stream()`` with no ``close``/``closing``/``with`` on ``x``
  anywhere in the same function.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional, Set

from netsdb_tpu.analysis.lint import (Diagnostic, Module, Rule,
                                      enclosing_functions, register,
                                      terminal_name)

#: method names producing lock-holding / thread-backed iterators
_PRODUCER_METHODS = {"stream", "stream_tables", "stream_host_tables",
                     "stream_blocks", "scan_stream"}
#: bare function names with the same contract
_PRODUCER_FUNCS = {"stage_stream"}

#: modules that IMPLEMENT the producers (their internals delegate and
#: re-yield; ownership rules differ inside)
_EXEMPT = ("netsdb_tpu/plan/staging.py",)


def _is_producer_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) \
            and f.attr in (_PRODUCER_METHODS | _PRODUCER_FUNCS):
        return True  # x.stream(...) AND staging.stage_stream(...)
    if isinstance(f, ast.Name) and f.id in _PRODUCER_FUNCS:
        return True
    return False


@register
class IterCloseRule(Rule):
    """Stream iterators consumed without ``closing``/``close()``."""

    id = "iter-close"
    rationale = ("an abandoned stream iterator holds its relation's "
                 "read lock until GC — close deterministically")

    def select(self, mod: Module) -> bool:
        return mod.rel not in _EXEMPT

    def check_module(self, mod: Module) -> Iterable[Diagnostic]:
        for _cls, fn in mod.functions():
            yield from self._check_fn(mod, fn)

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
        """The function's nodes EXCLUDING nested def subtrees (those
        are visited as their own functions — own close scope)."""
        stack = [fn]
        while stack:
            node = stack.pop()
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_fn(self, mod: Module, fn: ast.AST) -> Iterable[Diagnostic]:
        owned: Set[int] = set()  # id() of producer Call nodes accounted
        assigns: List[tuple] = []  # (varname, call node)
        closed_vars: Set[str] = set()

        for node in self._own_nodes(fn):
            # ownership transfers
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if _is_producer_call(arg):
                        owned.add(id(arg))
            if isinstance(node, ast.Return) and node.value is not None \
                    and _is_producer_call(node.value):
                owned.add(id(node.value))
            if isinstance(node, ast.YieldFrom) \
                    and _is_producer_call(node.value):
                owned.add(id(node.value))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_producer_call(item.context_expr):
                        owned.add(id(item.context_expr))
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for gen in node.generators:
                    if _is_producer_call(gen.iter):
                        owned.add(id(gen.iter))
            # var bookkeeping
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_producer_call(node.value):
                assigns.append((node.targets[0].id, node.value))
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "close" \
                        and isinstance(f.value, ast.Name):
                    closed_vars.add(f.value.id)
                if terminal_name(f) == "closing" and node.args \
                        and isinstance(node.args[0], ast.Name):
                    closed_vars.add(node.args[0].id)

        for node in self._own_nodes(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and _is_producer_call(node.iter) \
                    and id(node.iter) not in owned:
                name = terminal_name(node.iter.func)
                yield self.diag(
                    mod, node.iter,
                    f"iterating {name}() directly — a break, early "
                    f"return or abandoned outer generator leaks its "
                    f"read lock; wrap in contextlib.closing(...)")
        for var, call in assigns:
            if id(call) in owned or var in closed_vars:
                continue
            name = terminal_name(call.func)
            # render the suggested try/finally as a diff riding the
            # diagnostic (--json "suggestion") — still human-applied,
            # which is the --fix safety gate for this shape (lazy
            # import: fix.py imports this module at top level)
            from netsdb_tpu.analysis.fix import suggest_close

            d = self.diag(
                mod, call,
                f"{var} = {name}() is never closed in this function — "
                f"close() it (try/finally or contextlib.closing) or "
                f"hand ownership to the caller")
            suggestion = suggest_close(mod, var, call)
            if suggestion:
                d = dataclasses.replace(d, suggestion=suggestion)
            yield d
