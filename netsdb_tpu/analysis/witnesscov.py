"""Static ↔ runtime lock-graph reconciliation — ``cli lint
--witness-coverage``.

Two graphs describe the same property from opposite sides:

* the **static** lock-order graph (``rules/locking.static_lock_edges``
  — lexical nesting + interprocedural call-through + the seeded
  hierarchy), which over-approximates: every ordering the code COULD
  exercise;
* the **dynamic** witness graph (``utils/locks.LockWitness`` — the
  rank edges a real run actually recorded), which under-approximates:
  only the orderings some thread interleaving DID exercise.

Diffing them turns two silent gaps into reports:

* a static edge the witness never saw is **untested concurrency** —
  an ordering the tier-1 suite never drives, where an inversion would
  ship unnoticed until production interleaves it;
* a dynamic edge the static graph never derived is a **static blind
  spot** — lock usage reaching through a call path the resolver
  cannot see (C-extension callbacks, higher-order dispatch), i.e.
  exactly where to improve the call graph next.

Neither direction is a FAILURE (the report exits 0): the value is the
diff itself, refreshed per run.  Both sides share one rank-token
grammar (``summaries.lock_token`` deliberately matches the
``TrackedLock("SetStore._lock")`` witness names), so reconciliation
is a set comparison, not a fuzzy match.  The uncovered-static count
exports as the ``analysis.witness_uncovered_edges`` gauge so the
scrape can trend it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from netsdb_tpu.analysis.lint import (Project, load_project,
                                      set_gauge)
from netsdb_tpu.analysis.rules.locking import (SEED_EDGES,
                                               static_lock_edges)


def load_witness_dump(path: str) -> List[dict]:
    """Read a ``LockWitness.dump()`` file → its edge records."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    edges = payload.get("edges")
    if not isinstance(edges, list):
        raise ValueError(f"{path}: not a witness dump "
                         f"(no 'edges' list)")
    return edges


def coverage(dynamic_edges: List[dict],
             project: Optional[Project] = None) -> Dict[str, Any]:
    """Reconcile the static graph with witness edge records.

    Returns ``{"covered", "static_uncovered", "dynamic_unpredicted",
    "static_total", "dynamic_total", "coverage"}`` where the edge
    lists carry the best site each side knows (static: file:line of
    the sighting, or "seed"; dynamic: the witness acquisition
    sites)."""
    if project is None:
        project = load_project()
    static = static_lock_edges(project)
    dyn: Dict[Tuple[str, str], dict] = {}
    for rec in dynamic_edges:
        a, b = rec.get("held"), rec.get("acquired")
        if isinstance(a, str) and isinstance(b, str):
            dyn.setdefault((a, b), rec)
    static_keys = {k for k in static
                   if not (k[0].startswith("*.")
                           or k[1].startswith("*."))}
    covered = sorted(static_keys & set(dyn))
    uncovered = sorted(static_keys - set(dyn))
    unpredicted = sorted(set(dyn) - static_keys)
    seeds = set(SEED_EDGES)

    def static_site(k: Tuple[str, str]) -> str:
        site = static.get(k)
        if site is None:
            return "seed (docs/ANALYSIS.md)" if k in seeds else "?"
        return site.describe()

    report = {
        "static_total": len(static_keys),
        "dynamic_total": len(dyn),
        "covered": [{"edge": list(k), "static_site": static_site(k)}
                    for k in covered],
        "static_uncovered": [
            {"edge": list(k), "static_site": static_site(k)}
            for k in uncovered],
        "dynamic_unpredicted": [
            {"edge": list(k), "sites": dyn[k].get("sites", []),
             "modes": dyn[k].get("modes", [])}
            for k in unpredicted],
        "coverage": (len(covered) / len(static_keys)
                     if static_keys else 1.0),
    }
    set_gauge("analysis.witness_uncovered_edges", len(uncovered))
    return report


def render(report: Dict[str, Any]) -> str:
    """Human-readable reconciliation readout."""
    lines = [
        f"witness coverage: {len(report['covered'])}/"
        f"{report['static_total']} static lock-order edges exercised "
        f"at runtime ({report['coverage']:.0%}); "
        f"{report['dynamic_total']} dynamic edges observed",
    ]
    if report["static_uncovered"]:
        lines.append(f"  untested concurrency "
                     f"({len(report['static_uncovered'])} static "
                     f"edges no run has exercised):")
        for rec in report["static_uncovered"]:
            a, b = rec["edge"]
            lines.append(f"    {a} -> {b}  [{rec['static_site']}]")
    if report["dynamic_unpredicted"]:
        lines.append(f"  static blind spots "
                     f"({len(report['dynamic_unpredicted'])} runtime "
                     f"edges the static graph never derived):")
        for rec in report["dynamic_unpredicted"]:
            a, b = rec["edge"]
            sites = ", ".join(rec.get("sites") or ())
            lines.append(f"    {a} -> {b}  [{sites}]")
    return "\n".join(lines)
