"""Concurrency-correctness toolchain: AST lint + runtime lock witness.

Static half: :func:`run_lint` runs typed, pluggable AST rules
(``analysis/rules/``) over the package tree — lock-ordering cycles,
holds-across-blocking-calls, shared-state races, resource discipline,
and every ported pre-framework check — surfaced through ``python -m
netsdb_tpu.cli lint``.  The concurrency rules are INTERPROCEDURAL:
``analysis/callgraph.py`` resolves a project-wide call graph (module
imports, methods, attribute types, aliases, thread roots) and
``analysis/summaries.py`` folds it into transitive per-function lock
and blocking summaries.  Dynamic half: ``utils/locks.LockWitness``
(lockdep-style) records the cross-thread acquisition-order graph at
runtime and flags cycles that never fired; ``analysis/witnesscov.py``
reconciles the two graphs (``cli lint --witness-coverage``).
``analysis/baseline.py`` is the shrink-only findings ratchet.
``docs/ANALYSIS.md`` is the human catalog; the
``analysis-docs-drift`` rule keeps it honest.
"""

from netsdb_tpu.analysis.lint import (  # noqa: F401
    Diagnostic, Module, Project, Rule, all_rules, render, rule_ids,
    run_lint, to_json)
