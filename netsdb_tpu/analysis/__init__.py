"""Concurrency-correctness toolchain: AST lint + runtime lock witness.

Static half: :func:`run_lint` runs typed, pluggable AST rules
(``analysis/rules/``) over the package tree — lock-ordering cycles,
holds-across-blocking-calls, resource discipline, and every ported
pre-framework check — surfaced through ``python -m netsdb_tpu.cli
lint``.  Dynamic half: ``utils/locks.LockWitness`` (lockdep-style)
records the cross-thread acquisition-order graph at runtime and flags
cycles that never fired.  ``docs/ANALYSIS.md`` is the human catalog;
the ``analysis-docs-drift`` rule keeps it honest.
"""

from netsdb_tpu.analysis.lint import (  # noqa: F401
    Diagnostic, Module, Project, Rule, all_rules, render, rule_ids,
    run_lint, to_json)
