"""Findings baseline / ratchet — ``cli lint --baseline <file>``.

New interprocedural rules should land STRICT without demanding a
big-bang suppression sweep of pre-existing findings.  The baseline is
the middle path: a checked-in JSON file (``docs/lint_baseline.json``)
recording the findings the team has accepted *for now*.

Ratchet semantics:

* a finding matching a baseline entry is **accepted** — reported as
  baselined, not a failure;
* a finding matching NO entry is **new** — it fails, exactly as
  without a baseline (the ratchet never loosens);
* a baseline entry matching NO finding is **stale** — and a stale
  entry is itself a finding (``stale-baseline``): when a debt item is
  fixed, the baseline must shrink in the same change
  (``--write-baseline`` regenerates it), so the file can only ever
  ratchet toward empty.

Matching is by ``(rule, path, normalized message)`` — line numbers
and other digits are normalized out so unrelated edits shifting a
finding by a few lines don't churn the file; moving a finding to a
different file or changing what it says is a different finding.
Acceptance is COUNTED: an entry records how many occurrences of its
shape were accepted, so adding an Nth+1 duplicate of a baselined
finding still fails, and fixing one of N occurrences makes the entry
stale until the count shrinks.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Tuple

from netsdb_tpu.analysis.lint import REPO, STALE_BASELINE, Diagnostic

_VERSION = 1
_NUM_RE = re.compile(r"\d+")

Fingerprint = Tuple[str, str, str]


def fingerprint(rule: str, path: str, message: str) -> Fingerprint:
    """Line numbers (and every other digit run) normalize to ``N`` so
    the baseline survives unrelated line drift."""
    return (rule, path, _NUM_RE.sub("N", message))


def load(path: str) -> List[Dict[str, str]]:
    """Read a baseline file → its entry list ([] for a missing file —
    an absent baseline accepts nothing, same as no flag)."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    entries = payload.get("findings")
    if not isinstance(entries, list):
        raise ValueError(f"{path}: not a lint baseline "
                         f"(no 'findings' list)")
    return entries


def apply(diags: List[Diagnostic], baseline_path: str,
          repo: str = REPO) -> Tuple[List[Diagnostic],
                                     List[Diagnostic]]:
    """Split ``diags`` against the baseline.

    Returns ``(surviving, accepted)`` where ``surviving`` is the
    failures — new findings plus one ``stale-baseline`` diagnostic
    per entry that no longer matches anything — and ``accepted`` is
    the baselined findings (reported, not failed)."""
    entries = load(baseline_path)
    by_fp: Dict[Fingerprint, Dict[str, object]] = {}
    remaining: Dict[Fingerprint, int] = {}
    for e in entries:
        fp = fingerprint(str(e.get("rule", "")),
                         str(e.get("path", "")),
                         str(e.get("message", "")))
        by_fp[fp] = e
        # counted acceptance: one entry absorbs exactly the recorded
        # number of occurrences — an Nth+1 duplicate of a baselined
        # finding shape is a NEW finding, so the ratchet never
        # loosens (entries written before counts existed accept 1)
        remaining[fp] = remaining.get(fp, 0) + int(e.get("count", 1))
    matched: Dict[Fingerprint, int] = {}
    surviving: List[Diagnostic] = []
    accepted: List[Diagnostic] = []
    for d in diags:
        fp = fingerprint(d.rule, d.path, d.message)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            matched[fp] = matched.get(fp, 0) + 1
            accepted.append(d)
        else:
            surviving.append(d)
    rel = os.path.relpath(os.path.abspath(baseline_path),
                          repo).replace(os.sep, "/")
    for fp, e in sorted(by_fp.items()):
        left = remaining.get(fp, 0)
        if left <= 0:
            continue
        got = matched.get(fp, 0)
        what = "no longer matches any finding — the debt was paid" \
            if got == 0 else \
            f"records {got + left} occurrence(s) but only {got} " \
            f"remain — part of the debt was paid"
        surviving.append(Diagnostic(
            rule=STALE_BASELINE, path=rel, line=1, col=0,
            message=f"baseline entry {what}; shrink it (rule "
                    f"{e.get('rule')!r} at {e.get('path')!r}: "
                    f"{str(e.get('message', ''))[:120]!r}) or "
                    f"regenerate with --write-baseline"))
    surviving.sort(key=lambda d: (d.path, d.line, d.col, d.rule))
    return surviving, accepted


def write(diags: List[Diagnostic], baseline_path: str) -> int:
    """Record ``diags`` as the new accepted baseline; returns the
    entry count. An empty findings list writes an empty baseline —
    the goal state."""
    by_fp: Dict[Fingerprint, Dict[str, object]] = {}
    order: List[Fingerprint] = []
    for d in sorted(diags, key=lambda d: (d.path, d.rule, d.line)):
        fp = fingerprint(d.rule, d.path, d.message)
        if fp in by_fp:
            by_fp[fp]["count"] = int(by_fp[fp]["count"]) + 1
            continue
        order.append(fp)
        by_fp[fp] = {"rule": d.rule, "path": d.path,
                     "message": d.message, "count": 1}
    entries = [by_fp[fp] for fp in order]
    payload = {"version": _VERSION, "findings": entries}
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(entries)
