"""Mechanical auto-fixes for lint findings — ``cli lint --fix``.

The ``iter-close`` rule's commonest finding is purely mechanical::

    for chunk in pc.stream_tables():      # flagged: direct iteration
        ...

    with contextlib.closing(pc.stream_tables()) as _closing_stream:
        for chunk in _closing_stream:     # fixed
            ...

This module applies exactly that rewrite: wrap the producer call in
``contextlib.closing`` one statement up, iterate the bound name, indent
the loop body, and add ``import contextlib`` when the module lacks it.
Only statement-``for`` findings are fixed (the rule's other shape — an
assigned stream never closed — needs a ``try/finally`` whose extent a
human must choose, so it is reported, never rewritten).

Safety gates (a skipped fix is counted and reported, never guessed):

* the ``for`` header must be single-line (its iterator expression ends
  on the ``for`` line);
* the loop may not contain a multi-line string constant (re-indenting
  its lines would corrupt the literal);
* the generated binding name is collision-checked against the whole
  module source.

The rewrite is IDEMPOTENT by construction: after fixing, the loop
iterates a plain name, which the rule does not flag — re-running
``--fix`` finds nothing to do. ``--fix --dry-run`` renders the unified
diff without touching any file.
"""

from __future__ import annotations

import ast
import difflib
import os
from typing import Dict, List, Optional, Tuple

from netsdb_tpu.analysis.lint import REPO, Module, load_project
from netsdb_tpu.analysis.rules.resources import _is_producer_call

#: base name for the closing binding (numbered on collision)
_BIND = "_closing_stream"


def _has_multiline_string(node: ast.AST) -> bool:
    """Any str/bytes constant or f-string spanning lines — re-indenting
    its lines would change the literal's VALUE, not just layout."""
    for sub in ast.walk(node):
        multiline = getattr(sub, "end_lineno", None) is not None \
            and sub.end_lineno != getattr(sub, "lineno", None)
        if not multiline:
            continue
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, (str, bytes)):
            return True
        if isinstance(sub, ast.JoinedStr):
            return True
    return False


def _flagged_for_sites(mod: Module) -> set:
    """(line, col) of producer calls the iter-close rule flags as
    direct statement-``for`` iteration — the fixer rewrites exactly
    the sites the rule reports (ownership analysis stays in ONE
    place, the rule)."""
    from netsdb_tpu.analysis.rules.resources import IterCloseRule

    rule = IterCloseRule()
    if not rule.select(mod):
        return set()
    return {(d.line, d.col) for d in rule.check_module(mod)
            if "iterating" in d.message}


def _pick_name(source: str) -> str:
    name = _BIND
    k = 2
    while name in source:
        name = f"{_BIND}{k}"
        k += 1
    return name


def _ensure_import(lines: List[str]) -> Tuple[List[str], bool]:
    """Insert ``import contextlib`` after the module's import header
    when missing. Returns (lines, inserted). The presence check is an
    AST walk over MODULE-LEVEL imports — a function-local import or a
    docstring merely containing the text must not satisfy it (the
    rewritten loop's scope would hit NameError)."""
    try:
        tree = ast.parse("\n".join(lines))
    except SyntaxError:
        return lines, False
    # the rewrite emits `contextlib.closing(...)`, so only a top-level
    # unaliased `import contextlib` binds the name it needs (a
    # `from contextlib import closing` would not)
    for node in tree.body:
        if isinstance(node, ast.Import) \
                and any(a.name == "contextlib" and a.asname is None
                        for a in node.names):
            return lines, False
    insert_at = 0  # after the module docstring and import header
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            insert_at = getattr(node, "end_lineno", node.lineno)
        elif isinstance(node, ast.Expr) and insert_at == 0 \
                and isinstance(node.value, ast.Constant):
            insert_at = getattr(node, "end_lineno", node.lineno)
        else:
            break
    out = list(lines)
    out.insert(insert_at, "import contextlib")
    return out, True




def _one_pass(mod: Module) -> Tuple[Optional[str], int, int]:
    """One rewrite pass over ``mod``: fixes only INNERMOST flagged
    loops (an outer flagged loop containing another flagged loop is
    deferred — rewriting it with stale line numbers after the inner
    rewrite grew the file would corrupt the source; the caller
    iterates to a fixed point). Returns ``(new_source | None, fixed,
    skipped)``."""
    if mod.tree is None:
        return None, 0, 0
    flagged = _flagged_for_sites(mod)
    if not flagged:
        return None, 0, 0
    loops: List[ast.For] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and _is_producer_call(node.iter) \
                and (node.iter.lineno, node.iter.col_offset) in flagged:
            loops.append(node)
    if not loops:
        return None, 0, 0
    # innermost-only: defer any loop whose span contains another
    # flagged loop (the next pass sees fresh line numbers)
    innermost = [a for a in loops
                 if not any(b is not a and a.lineno < b.lineno
                            and b.end_lineno <= a.end_lineno
                            for b in loops)]
    lines = list(mod.lines)
    fixed = 0
    skipped = 0
    # bottom-up so earlier line numbers stay valid across rewrites
    for node in sorted(innermost, key=lambda n: -n.lineno):
        header_ok = (node.iter.end_lineno == node.lineno
                     and node.body and node.body[0].lineno > node.lineno)
        if not header_ok or _has_multiline_string(node):
            skipped += 1
            continue
        expr_src = ast.get_source_segment(mod.source, node.iter)
        if expr_src is None:
            skipped += 1
            continue
        name = _pick_name("\n".join(lines))
        indent = " " * node.col_offset
        li = node.lineno - 1
        header = lines[li]
        new_for = (header[:node.iter.col_offset] + name
                   + header[node.iter.end_col_offset:])
        block = [indent + f"with contextlib.closing({expr_src}) "
                          f"as {name}:",
                 "    " + new_for]
        for bl in lines[node.lineno:node.end_lineno]:
            block.append("    " + bl if bl.strip() else bl)
        lines[li:node.end_lineno] = block
        fixed += 1
    if not fixed:
        return None, 0, skipped
    lines, _ = _ensure_import(lines)
    new_source = "\n".join(lines)
    if mod.source.endswith("\n"):
        new_source += "\n"
    return new_source, fixed, skipped


def fix_module(mod: Module, repo: str = REPO
               ) -> Tuple[Optional[str], int, int]:
    """Compute the fixed source for one module, iterating
    :func:`_one_pass` to a fixed point (nested flagged loops fix
    inside-out across passes, each pass re-linting a freshly parsed
    in-memory :class:`Module` over the rewritten source).

    Returns ``(new_source | None, fixed, skipped)`` — ``None`` when
    nothing changed; ``skipped`` counts flagged loops the safety gates
    refused to rewrite (the stable remainder after the final pass)."""
    total_fixed = 0
    skipped = 0
    cur = mod
    for _ in range(8):  # depth bound; real nesting is 1-2 deep
        new_source, fixed, skipped = _one_pass(cur)
        if new_source is None:
            break
        total_fixed += fixed
        cur = Module(mod.path, repo, source=new_source)
    if total_fixed == 0:
        return None, 0, skipped
    return cur.source, total_fixed, skipped


#: builtins that EAGERLY drain whatever iterator chain they are
#: handed — a value they produce cannot keep the handle alive
_EAGER = {"next", "list", "tuple", "set", "dict", "sorted", "sum",
          "min", "max", "any", "all", "len"}
#: builtins that LAZILY rewrap an iterator — the wrapper holds the
#: live handle, so returning/storing one IS an escape of the handle
_LAZY = {"iter", "enumerate", "zip", "map", "filter", "reversed"}


def _escapes(stmt: ast.AST, var: str) -> bool:
    """Whether the live handle ``var`` escapes through ``stmt``:
    returned/yielded/stored directly, inside a container literal,
    inside a LAZY rewrapper (``return enumerate(var)``, a generator
    expression over it), or passed bare to a non-builtin call
    (``register(var)``, ``self.cache.append(var)``).  Values whose
    outermost operation eagerly drains the chain (``return
    next(iter(var))``, ``rows = list(var)``) — or method calls on the
    handle itself — are consumption, not escape."""
    from netsdb_tpu.analysis.lint import terminal_name

    def contains_var(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id == var
                   for n in ast.walk(node))

    def derives_safely(value: ast.AST) -> bool:
        # list/set/dict comprehensions drain eagerly (a GENERATOR
        # expression stays lazy and falls through to escape)
        if isinstance(value, (ast.ListComp, ast.SetComp,
                              ast.DictComp)):
            return True
        if not isinstance(value, ast.Call):
            return False
        if terminal_name(value.func) in _EAGER:
            return True
        # a method call ON the handle (var.read(), var.close())
        # returns derived data, not the handle
        f = value.func
        return isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Name) and f.value.id == var

    def is_bare_var(node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id == var:
            return True
        if isinstance(node, ast.Starred):
            return is_bare_var(node.value)
        return False

    for node in ast.walk(stmt):
        value = None
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            value = node.value
        elif isinstance(node, (ast.Assign, ast.AnnAssign,
                               ast.AugAssign)):
            value = node.value
        if value is not None and contains_var(value) \
                and not derives_safely(value):
            return True
        if isinstance(node, ast.Call):
            fname = terminal_name(node.func)
            f = node.func
            on_var = isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) and f.value.id == var
            if fname not in _EAGER and fname not in _LAZY \
                    and not on_var:
                args = list(node.args) + [kw.value
                                          for kw in node.keywords]
                if any(is_bare_var(a) for a in args):
                    return True
    return False


def suggest_close(mod: Module, var: str,
                  call: ast.AST) -> Optional[str]:
    """Render a SUGGESTED ``try/finally`` diff for the iter-close
    rule's assigned-never-closed shape::

        it = pc.stream()          →    it = pc.stream()
        <rest of block>                try:
                                           <rest of block>
                                       finally:
                                           it.close()

    The extent (rest of the enclosing block) is a best-effort default
    a human still reviews — which is exactly why this renders a diff
    in the report instead of rewriting the file (the ``--fix`` safety
    gate).  Returns a unified diff, or None when a safety gate
    (multi-line statements, nothing after the assignment) says a
    mechanical suggestion would be wrong."""
    if mod.tree is None:
        return None
    assign = None
    body: Optional[List[ast.stmt]] = None
    for node in ast.walk(mod.tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, ast.Assign) \
                        and stmt.value is call:
                    assign, body, idx = stmt, stmts, i
    if assign is None or body is None:
        return None
    rest = body[idx + 1:]
    if not rest:
        return None  # created and never used: closing extent unclear
    if assign.end_lineno != assign.lineno:
        return None
    if any(_has_multiline_string(stmt) for stmt in rest):
        return None
    if any(_escapes(stmt, var) for stmt in rest):
        # the handle itself leaves the function (returned, yielded,
        # aliased, stored) — a finally: close() here would hand the
        # caller a closed iterator; no mechanical suggestion is right
        return None
    indent = " " * assign.col_offset
    lines = list(mod.lines)
    start = rest[0].lineno - 1
    end = rest[-1].end_lineno  # exclusive slice bound
    block = [indent + "try:"]
    for bl in lines[start:end]:
        block.append("    " + bl if bl.strip() else bl)
    block += [indent + "finally:", indent + f"    {var}.close()"]
    new_lines = lines[:start] + block + lines[end:]
    new_source = "\n".join(new_lines)
    if mod.source.endswith("\n"):
        new_source += "\n"
    return "".join(difflib.unified_diff(
        mod.source.splitlines(keepends=True),
        new_source.splitlines(keepends=True),
        fromfile=f"a/{mod.rel}", tofile=f"b/{mod.rel}"))


def run_fix(paths: Optional[List[str]] = None, repo: str = REPO,
            dry_run: bool = False) -> Dict[str, object]:
    """Apply (or preview) the iter-close fixes over ``paths`` (default:
    the whole package tree). Returns ``{"fixed": n, "skipped": n,
    "files": [rel...], "diff": str}`` — ``diff`` is populated only for
    dry runs; real runs write the files in place."""
    project = load_project(paths, repo)
    total_fixed = 0
    total_skipped = 0
    files: List[str] = []
    diffs: List[str] = []
    for mod in project.modules:
        new_source, fixed, skipped = fix_module(mod, repo)
        total_skipped += skipped
        if new_source is None:
            continue
        total_fixed += fixed
        files.append(mod.rel)
        if dry_run:
            diffs.append("".join(difflib.unified_diff(
                mod.source.splitlines(keepends=True),
                new_source.splitlines(keepends=True),
                fromfile=f"a/{mod.rel}", tofile=f"b/{mod.rel}")))
        else:
            tmp = mod.path + ".lintfix.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(new_source)
            os.replace(tmp, mod.path)
    return {"fixed": total_fixed, "skipped": total_skipped,
            "files": files, "diff": "".join(diffs)}
