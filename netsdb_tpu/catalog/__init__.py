from netsdb_tpu.catalog.catalog import Catalog

__all__ = ["Catalog"]
