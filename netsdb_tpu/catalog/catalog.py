"""Metadata catalog — databases, sets, registered types, nodes.

TPU-native analogue of ``PDBCatalog`` over sqlite_orm (reference
``src/catalog/headers/PDBCatalog.h:45-50``, ``PDBCatalogStorage.h:8-26``),
which tracks PDBCatalogDatabase/Set/Node/Type rows and replicates
registered user-type .so binaries master→workers. Here:

- databases and sets persist in sqlite exactly as in the reference;
- "types" are registered Python op/model entry points (dotted import
  paths) instead of .so binaries — JAX needs no dynamic native loading;
- "nodes" describe the device mesh topology instead of worker hosts; the
  data plane is XLA collectives, so node rows are informational + used by
  the placement advisor.

Sets additionally carry tensor metadata (dtype/shape/block shape/sharding
spec/host path), which the reference keeps inside Pangea rather than the
catalog — folding it in here gives one source of truth.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional


_SCHEMA = """
CREATE TABLE IF NOT EXISTS databases (
    name TEXT PRIMARY KEY,
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS sets (
    db_name TEXT NOT NULL,
    set_name TEXT NOT NULL,
    type_name TEXT NOT NULL DEFAULT 'tensor',
    meta_json TEXT NOT NULL DEFAULT '{}',
    persistence TEXT NOT NULL DEFAULT 'transient',
    host_path TEXT,
    created_at REAL NOT NULL,
    PRIMARY KEY (db_name, set_name)
);
CREATE TABLE IF NOT EXISTS types (
    type_name TEXT PRIMARY KEY,
    entry_point TEXT NOT NULL,
    registered_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS nodes (
    node_id INTEGER PRIMARY KEY,
    address TEXT NOT NULL,
    num_devices INTEGER NOT NULL,
    device_kind TEXT NOT NULL
);
"""


class Catalog:
    """Sqlite-backed metadata store. Thread-safe via a single lock
    (the reference serializes catalog access the same way through its
    server handler queue)."""

    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            # migration for catalogs created before round 3: types grew
            # a source column (shipped UDF code, the .so-bytes analogue)
            try:
                self._conn.execute("ALTER TABLE types ADD COLUMN source TEXT")
            except sqlite3.OperationalError:
                pass  # column already exists
            self._conn.commit()

    # --- databases (ref: PDBCatalog::registerDatabase) ----------------
    def create_database(self, name: str) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO databases VALUES (?, ?)", (name, time.time())
            )
            self._conn.commit()

    def database_exists(self, name: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "SELECT 1 FROM databases WHERE name = ?", (name,)
            )
            return cur.fetchone() is not None

    def list_databases(self) -> List[str]:
        with self._lock:
            cur = self._conn.execute("SELECT name FROM databases ORDER BY name")
            return [r[0] for r in cur.fetchall()]

    def drop_database(self, name: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM sets WHERE db_name = ?", (name,))
            self._conn.execute("DELETE FROM databases WHERE name = ?", (name,))
            self._conn.commit()

    # --- sets (ref: PDBCatalog::registerSet) --------------------------
    def create_set(
        self,
        db_name: str,
        set_name: str,
        type_name: str = "tensor",
        meta: Optional[Dict] = None,
        persistence: str = "transient",
        host_path: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO sets VALUES (?, ?, ?, ?, ?, ?, ?)",
                (
                    db_name,
                    set_name,
                    type_name,
                    json.dumps(meta or {}),
                    persistence,
                    host_path,
                    time.time(),
                ),
            )
            self._conn.commit()

    def set_exists(self, db_name: str, set_name: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "SELECT 1 FROM sets WHERE db_name = ? AND set_name = ?",
                (db_name, set_name),
            )
            return cur.fetchone() is not None

    def get_set(self, db_name: str, set_name: str) -> Optional[Dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT type_name, meta_json, persistence, host_path FROM sets "
                "WHERE db_name = ? AND set_name = ?",
                (db_name, set_name),
            )
            row = cur.fetchone()
        if row is None:
            return None
        return {
            "db": db_name,
            "set": set_name,
            "type": row[0],
            "meta": json.loads(row[1]),
            "persistence": row[2],
            "host_path": row[3],
        }

    def update_set_meta(self, db_name: str, set_name: str, meta: Dict) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE sets SET meta_json = ? WHERE db_name = ? AND set_name = ?",
                (json.dumps(meta), db_name, set_name),
            )
            self._conn.commit()

    def list_sets(self, db_name: Optional[str] = None) -> List[Dict]:
        with self._lock:
            if db_name is None:
                cur = self._conn.execute("SELECT db_name, set_name FROM sets")
            else:
                cur = self._conn.execute(
                    "SELECT db_name, set_name FROM sets WHERE db_name = ?", (db_name,)
                )
            return [{"db": r[0], "set": r[1]} for r in cur.fetchall()]

    def remove_set(self, db_name: str, set_name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM sets WHERE db_name = ? AND set_name = ?",
                (db_name, set_name),
            )
            self._conn.commit()

    # --- types (ref: PDBCatalog registered user types / .so files) ----
    def register_type(self, type_name: str, entry_point: str,
                      source: Optional[str] = None) -> None:
        """``source`` (optional Python module text) is the analogue of
        the reference catalog storing and replicating user-type .so
        binaries so workers can execute types they have never imported
        (``src/catalog/headers/PDBCatalog.h:45-50``): the serve daemon
        loads it when the entry point's module is not installed."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO types "
                "(type_name, entry_point, registered_at, source) "
                "VALUES (?, ?, ?, ?)",
                (type_name, entry_point, time.time(), source),
            )
            self._conn.commit()

    def get_type(self, type_name: str) -> Optional[str]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT entry_point FROM types WHERE type_name = ?", (type_name,)
            )
            row = cur.fetchone()
        return row[0] if row else None

    def get_type_source(self, type_name: str) -> Optional[str]:
        """Shipped module source for a registered type, if any."""
        with self._lock:
            cur = self._conn.execute(
                "SELECT source FROM types WHERE type_name = ?", (type_name,)
            )
            row = cur.fetchone()
        return row[0] if row else None

    def list_types(self) -> List[Dict]:
        with self._lock:
            cur = self._conn.execute("SELECT type_name, entry_point FROM types")
            return [{"type": r[0], "entry_point": r[1]} for r in cur.fetchall()]

    # --- nodes (ref: PDBCatalogNode / conf/serverlist) ----------------
    def register_node(
        self, node_id: int, address: str, num_devices: int, device_kind: str
    ) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO nodes VALUES (?, ?, ?, ?)",
                (node_id, address, num_devices, device_kind),
            )
            self._conn.commit()

    def list_nodes(self) -> List[Dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT node_id, address, num_devices, device_kind FROM nodes"
            )
            return [
                {
                    "node_id": r[0],
                    "address": r[1],
                    "num_devices": r[2],
                    "device_kind": r[3],
                }
                for r in cur.fetchall()
            ]

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def read_module_source(entry_point: str) -> str:
    """Read the source text of an entry point's locally-importable
    module — the client-side half of UDF code shipping
    (``register_type(ship_module=True)``; the reference reads the .so
    bytes off disk to replicate them, ``PDBCatalog.h:45-50``)."""
    import importlib.util

    spec = importlib.util.find_spec(entry_point.partition(":")[0])
    if spec is None or spec.origin is None:
        raise ImportError(
            f"ship_module: cannot locate source for {entry_point!r}")
    with open(spec.origin, "r") as f:
        return f.read()
