from netsdb_tpu.dsl.interp import LAInterpreter, run_pdml
from netsdb_tpu.dsl.parser import parse_program

__all__ = ["LAInterpreter", "run_pdml", "parse_program"]
