"""PDML parser — the reference's linear-algebra DSL grammar, hand-rolled.

Re-implements the flex/bison grammar (reference
``src/linearAlgebraDSL/source/LALexer.l``, ``LAParser.y``) as a
recursive-descent parser with the same precedence structure:

    statement  := IDENT '=' expression
    expression := additive
    additive   := mult (('+'|'-') mult)*            # left-assoc
    mult       := postfix (('%*%'|'*'|"'*") postfix)*  # matmul / scale / Aᵀ·B
    postfix    := primary ['^T' | '^-1']
    primary    := IDENT | initializer | builtin '(' ... ')' | '(' expression ')'
    initializer:= load(brS,bcS,brN,bcN,"path") | zeros/ones(brS,bcS,brN,bcN)
                | identity(blockSize, blockNum)
    builtin    := max min rowMax rowMin rowSum colMax colMin colSum
                | duplicateRow(expr, brS, brN) | duplicateCol(expr, bcS, bcN)

Dimension arguments follow the reference convention (block sizes and
block counts, see ``DSLSamples/sample00_Parser.pdml`` and the
TestDataGenerator scripts).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple, Union

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|\#[^\n]*)
  | (?P<matmul>%\*%)
  | (?P<tmul>'\*)
  | (?P<transpose>\^T)
  | (?P<inverse>\^-1)
  | (?P<num>\d+\.\d*|\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<string>"[^"]*")
  | (?P<op>[=+\-*(),])
    """,
    re.VERBOSE,
)

_BUILTIN_REDUCE = {"max", "min", "rowMax", "rowMin", "rowSum",
                   "colMax", "colMin", "colSum"}
_INITIALIZERS = {"load", "zeros", "ones", "identity"}


@dataclasses.dataclass
class Node:
    kind: str  # ident|init|unop|binop|reduce|duplicate
    value: Union[str, float, None] = None
    children: Tuple["Node", ...] = ()
    args: Tuple = ()

    def __repr__(self):
        return f"Node({self.kind},{self.value},{self.children},{self.args})"


@dataclasses.dataclass
class Statement:
    target: str
    expr: Node


def tokenize(text: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SyntaxError(f"bad character {text[pos]!r} at offset {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> None:
        kind, val = self.next()
        if val != text:
            raise SyntaxError(f"expected {text!r}, got {val!r}")

    def parse_program(self) -> List[Statement]:
        stmts = []
        while self.peek()[0] != "eof":
            stmts.append(self.parse_statement())
        return stmts

    def parse_statement(self) -> Statement:
        kind, name = self.next()
        if kind != "ident":
            raise SyntaxError(f"expected identifier, got {name!r}")
        self.expect("=")
        return Statement(name, self.parse_expression())

    def parse_expression(self) -> Node:
        node = self.parse_mult()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            rhs = self.parse_mult()
            node = Node("binop", "add" if op == "+" else "subtract",
                        (node, rhs))
        return node

    def parse_mult(self) -> Node:
        node = self.parse_postfix()
        while True:
            kind, val = self.peek()
            if kind == "matmul":
                self.next()
                node = Node("binop", "multiply", (node, self.parse_postfix()))
            elif kind == "tmul":
                self.next()
                node = Node("binop", "transpose_multiply",
                            (node, self.parse_postfix()))
            elif val == "*":
                self.next()
                node = Node("binop", "scale_multiply",
                            (node, self.parse_postfix()))
            else:
                return node

    def parse_postfix(self) -> Node:
        node = self.parse_primary()
        kind, _ = self.peek()
        if kind == "transpose":
            self.next()
            return Node("unop", "transpose", (node,))
        if kind == "inverse":
            self.next()
            return Node("unop", "inverse", (node,))
        return node

    def _int_args(self, n: int) -> Tuple[int, ...]:
        vals = []
        for k in range(n):
            kind, v = self.next()
            if kind != "num":
                raise SyntaxError(f"expected integer, got {v!r}")
            vals.append(int(float(v)))
            if k < n - 1:
                self.expect(",")
        return tuple(vals)

    def parse_primary(self) -> Node:
        kind, val = self.peek()
        if val == "(":
            self.next()
            node = self.parse_expression()
            self.expect(")")
            return node
        if kind != "ident":
            raise SyntaxError(f"unexpected token {val!r}")
        self.next()
        if val in _INITIALIZERS:
            self.expect("(")
            if val == "identity":
                args = self._int_args(2)
                self.expect(")")
                return Node("init", "identity", args=args)
            if val == "load":
                args = self._int_args(4)
                self.expect(",")
                skind, sval = self.next()
                if skind != "string":
                    raise SyntaxError(f"load path must be a string, got {sval!r}")
                self.expect(")")
                return Node("init", "load", args=args + (sval[1:-1],))
            args = self._int_args(4)
            self.expect(")")
            return Node("init", val, args=args)
        if val in _BUILTIN_REDUCE:
            self.expect("(")
            inner = self.parse_expression()
            self.expect(")")
            return Node("reduce", val, (inner,))
        if val in ("duplicateRow", "duplicateCol"):
            self.expect("(")
            inner = self.parse_expression()
            self.expect(",")
            args = self._int_args(2)
            self.expect(")")
            return Node("duplicate", val, (inner,), args)
        return Node("ident", val)


def parse_program(text: str) -> List[Statement]:
    return _Parser(tokenize(text)).parse_program()
