"""PDML interpreter — evaluates parsed statements over the op layer.

The reference walks its AST instantiating ``libLASilly*`` Computation
.so objects and calling executeComputations per statement
(``src/linearAlgebraDSL/source/LAEvaluateFunctions.cc``, driver
``TestLA21_Instance.cc``); results land in sets named by an
``LAPDBInstance``. Here each statement evaluates to a
``BlockedTensor`` (scalars stay 1x1) bound in an environment, with the
same operator semantics (``netsdb_tpu.ops.linalg``); results can be
materialized into client sets for parity with the set-oriented flow.

``load`` reads the reference's block-per-line text format
(``TestDataGenerator/GramTestDataGenerator.py``: each line =
"blockRow blockCol v... (row-major block)") plus ``.npy`` arrays.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import linalg as la
from netsdb_tpu.dsl.parser import Node, Statement, parse_program


def load_block_file(path: str, block_rows: int, block_cols: int,
                    block_row_num: int, block_col_num: int) -> np.ndarray:
    """Reference .data format: one block per line."""
    if path.endswith(".npy"):
        arr = np.load(path)
        expect = (block_rows * block_row_num, block_cols * block_col_num)
        if arr.shape != expect:
            raise ValueError(f"{path}: shape {arr.shape} != declared {expect}")
        return arr.astype(np.float32)
    out = np.zeros((block_rows * block_row_num, block_cols * block_col_num),
                   dtype=np.float32)
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            bi, bj = int(parts[0]), int(parts[1])
            vals = np.asarray(parts[2:], dtype=np.float32)
            if vals.size != block_rows * block_cols:
                raise ValueError(
                    f"{path}: block ({bi},{bj}) has {vals.size} values, "
                    f"expected {block_rows * block_cols}")
            out[bi * block_rows:(bi + 1) * block_rows,
                bj * block_cols:(bj + 1) * block_cols] = (
                vals.reshape(block_rows, block_cols))
    return out


class LAInterpreter:
    """Environment of name → BlockedTensor (the LAPDBInstance role)."""

    def __init__(self, client=None, db: str = "la"):
        self.env: Dict[str, BlockedTensor] = {}
        self.client = client
        self.db = db
        if client is not None:
            client.create_database(db)

    def run(self, text: str) -> Dict[str, BlockedTensor]:
        for stmt in parse_program(text):
            self.execute(stmt)
        return self.env

    def execute(self, stmt: Statement) -> BlockedTensor:
        value = self.eval(stmt.expr)
        self.env[stmt.target] = value
        if self.client is not None:
            # materialize per-statement results as sets (reference flow)
            if not self.client.set_exists(self.db, stmt.target):
                self.client.create_set(self.db, stmt.target)
            from netsdb_tpu.storage.store import SetIdentifier

            self.client.store.put_tensor(SetIdentifier(self.db, stmt.target),
                                         value)
        return value

    def eval(self, node: Node) -> BlockedTensor:
        if node.kind == "ident":
            if node.value not in self.env:
                raise NameError(f"undefined matrix {node.value!r}")
            return self.env[node.value]
        if node.kind == "init":
            return self._eval_init(node)
        if node.kind == "unop":
            x = self.eval(node.children[0])
            return la.transpose(x) if node.value == "transpose" else la.inverse(x)
        if node.kind == "binop":
            a = self.eval(node.children[0])
            b = self.eval(node.children[1])
            if node.value in ("add", "subtract", "scale_multiply"):
                # elementwise ops tolerate mixed block granularity (e.g. a
                # matmul result + a loaded matrix): align to a's blocking
                if a.meta.block_shape != b.meta.block_shape:
                    b = b.reblock(a.meta.block_shape)
            if node.value == "add":
                return la.add(a, b)
            if node.value == "subtract":
                return la.subtract(a, b)
            if node.value == "scale_multiply":
                return la.scale_multiply(a, b)
            if node.value == "multiply":
                return la.matmul(a, b)
            if node.value == "transpose_multiply":
                return la.t_matmul(a, b)
            raise ValueError(node.value)
        if node.kind == "reduce":
            x = self.eval(node.children[0])
            if node.value in ("max", "min"):
                fn = la.max_element if node.value == "max" else la.min_element
                scalar = fn(x)
                return BlockedTensor.from_dense(
                    jnp.asarray(scalar).reshape(1, 1), (1, 1))
            return {
                "rowMax": la.row_max, "rowMin": la.row_min,
                "rowSum": la.row_sum, "colMax": la.col_max,
                "colMin": la.col_min, "colSum": la.col_sum,
            }[node.value](x)
        if node.kind == "duplicate":
            x = self.eval(node.children[0])
            size, num = node.args
            if node.value == "duplicateRow":
                return la.duplicate_row(x, size * num, size)
            return la.duplicate_col(x, size * num, size)
        raise ValueError(f"unknown node {node.kind}")

    def _eval_init(self, node: Node) -> BlockedTensor:
        if node.value == "identity":
            size, num = node.args
            return la.identity(size * num, size)
        br_size, bc_size, br_num, bc_num = node.args[:4]
        rows, cols = br_size * br_num, bc_size * bc_num
        if node.value == "zeros":
            return la.zeros(rows, cols, br_size, bc_size)
        if node.value == "ones":
            return la.ones(rows, cols, br_size, bc_size)
        if node.value == "load":
            dense = load_block_file(node.args[4], br_size, bc_size,
                                    br_num, bc_num)
            return BlockedTensor.from_dense(dense, (br_size, bc_size))
        raise ValueError(node.value)


def run_pdml(text: str, client=None, db: str = "la") -> Dict[str, BlockedTensor]:
    """Parse + evaluate a PDML program (reference testLA21_Instance flow)."""
    return LAInterpreter(client, db).run(text)
