"""netsdb_tpu — a TPU-native in-database model-inference framework.

A ground-up JAX/XLA/pallas re-design of the capabilities of netsDB
(reference: /root/reference, a UDF-centric distributed analytics database
derived from PlinyCompute). netsDB expresses ML inference as relational
algebra over sets of blocked matrices executed by a hand-written C++
master/worker runtime; here the same capabilities are expressed TPU-first:

- sets of ``FFMatrixBlock`` objects (reference ``src/FF/headers/FFMatrixBlock.h``)
  become :class:`~netsdb_tpu.core.blocked.BlockedTensor` — one logical padded
  ``jax.Array`` whose block grid is the sharding granularity on a device mesh;
- the Lambda/Computation UDF DAG + TCAP IR (reference ``src/lambdas``,
  ``src/logicalPlan``) becomes a small logical plan IR lowered to jit stages;
- the master/worker socket shuffle (reference ``src/communication``,
  ``src/queryExecution/source/PipelineStage.cc``) becomes XLA collectives
  over ICI/DCN via ``jax.sharding`` + ``shard_map``;
- the Pangea storage engine (reference ``src/storage``) becomes a host-side
  set store with a C++ page-cache runtime streaming blocks into HBM.
"""

try:
    import jax as _jax  # noqa: F401  (probe only)
except ModuleNotFoundError:  # pragma: no cover
    # The image's PATH python has an empty site-packages; the real
    # environment lives in /opt/venv. ONLY for `python -m
    # netsdb_tpu[...]` invocations, re-exec the ORIGINAL command line
    # there — a plain `import netsdb_tpu` from some other broken
    # interpreter must fail normally, not hijack the process.
    from netsdb_tpu import _reexec

    _reexec.maybe_reexec("NETSDB_CLI_REEXEC",
                         require_module_prefix="netsdb_tpu")
    raise

from netsdb_tpu.config import Configuration
from netsdb_tpu.core.blocked import BlockedTensor, BlockMeta
from netsdb_tpu.catalog.catalog import Catalog
from netsdb_tpu.storage.store import SetStore, SetIdentifier
from netsdb_tpu.client import Client

__version__ = "0.1.0"

__all__ = [
    "Configuration",
    "BlockedTensor",
    "BlockMeta",
    "Catalog",
    "SetStore",
    "SetIdentifier",
    "Client",
    "__version__",
]
