"""Re-exec onto the packaged interpreter — stdlib only.

The image's PATH python has an empty site-packages; the real
environment (jax/numpy/torch) lives in /opt/venv. Entry points call
:func:`maybe_reexec` from their ModuleNotFoundError handlers to replace
the process with the venv interpreter re-running the ORIGINAL command
line (recovered from ``/proc/self/cmdline``, so ``-m pkg.submodule``
targets re-run exactly as requested rather than being rewritten).

Must not import anything outside the stdlib, and is loaded by file path
from ``bench.py`` (importing the package would re-trigger the very
ModuleNotFoundError being handled).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

VENV = "/opt/venv/bin/python"


def _original_argv() -> Optional[list]:
    """This process's full command line (linux); None if unrecoverable."""
    try:
        with open("/proc/self/cmdline", "rb") as f:
            raw = f.read()
    except OSError:
        return None
    args = [a.decode(errors="replace") for a in raw.split(b"\0") if a]
    return args or None


def maybe_reexec(flag: str,
                 require_module_prefix: Optional[str] = None) -> None:
    """Replace the process with ``/opt/venv/bin/python <original args>``.

    No-ops (returning so the caller can re-raise its import error) when
    the venv is missing, the loop-guard env ``flag`` is already set, the
    original command line cannot be recovered, or
    ``require_module_prefix`` is given and the command was not
    ``python -m <prefix>[...]`` — a plain ``import netsdb_tpu`` from
    some unrelated broken interpreter must fail normally, not have its
    process hijacked.
    """
    if not os.path.exists(VENV) or os.environ.get(flag):
        return
    args = _original_argv()
    if args is None:
        return
    if require_module_prefix is not None:
        # "-m" must be the interpreter's own option (directly after
        # argv[0]) — scanning the whole line would let a SCRIPT's
        # "-m netsdb_tpu" argument hijack `python my_tool.py -m
        # netsdb_tpu` into a re-exec. Interpreter flags before -m are
        # rare here; if present we conservatively decline.
        if len(args) < 3 or args[1] != "-m":
            return
        mod = args[2]
        if mod != require_module_prefix and not mod.startswith(
                require_module_prefix + "."):
            return
    os.environ[flag] = "1"
    os.execv(VENV, [VENV] + args[1:])
