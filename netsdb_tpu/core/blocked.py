"""Blocked tensors — the TPU-native replacement for netsDB's matrix-block sets.

netsDB represents a matrix as a *set* of ``FFMatrixBlock`` objects, each
carrying ``FFMatrixMeta`` (blockRowIndex, blockColIndex, totalRows, totalCols)
plus an Eigen-mapped ``Vector<double>`` payload
(reference ``src/FF/headers/FFMatrixBlock.h:18-156``, ``FFMatrixMeta.h``,
``FFMatrixData.h``). Distributed matmul is then an equi-join on the
contraction block index plus an aggregation over block products — SUMMA on a
relational engine (``src/FF/headers/FFTransposeMult.h:38-92``,
``FFAggMatrix.h:11-30``).

On TPU the idiomatic representation is ONE logical ``jax.Array`` padded up to
a whole number of blocks; the block grid is purely *metadata* that
(a) defines the sharding granularity on a device mesh and (b) preserves the
reference's ragged-last-block semantics (``FFMatrixBlock.h:79-87``) via
explicit padding + masking rather than dynamic shapes, which XLA cannot tile
onto the MXU.

``BlockedTensor`` is a pytree, so it traces through ``jax.jit`` with the
meta as static structure.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Shape = Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class BlockMeta:
    """Static metadata: logical (unpadded) shape + block shape.

    Equivalent of ``FFMatrixMeta`` fields totalRows/totalCols + the implicit
    block dims carried by every block's rowNums/colNums; one meta describes
    the whole tensor instead of one object per block.
    """

    shape: Shape  # logical, unpadded
    block_shape: Shape

    def __post_init__(self):
        if len(self.shape) != len(self.block_shape):
            raise ValueError(
                f"rank mismatch: shape {self.shape} vs block {self.block_shape}"
            )
        if any(b <= 0 for b in self.block_shape):
            raise ValueError(f"non-positive block shape {self.block_shape}")

    @property
    def rank(self) -> int:
        return len(self.shape)

    @property
    def grid(self) -> Shape:
        """Number of blocks along each dim (ceil-div, ragged last block padded)."""
        return tuple(-(-s // b) for s, b in zip(self.shape, self.block_shape))

    @property
    def padded_shape(self) -> Shape:
        return tuple(g * b for g, b in zip(self.grid, self.block_shape))

    @property
    def num_blocks(self) -> int:
        return int(math.prod(self.grid))

    @property
    def is_padded(self) -> bool:
        return self.padded_shape != self.shape

    def block_slice(self, index: Sequence[int]) -> Tuple[slice, ...]:
        """Slice of the padded array covered by block ``index``."""
        if len(index) != self.rank:
            raise ValueError(f"block index {index} has wrong rank for {self}")
        for i, (ix, g) in enumerate(zip(index, self.grid)):
            if not 0 <= ix < g:
                raise IndexError(f"block index {ix} out of range [0,{g}) on dim {i}")
        return tuple(
            slice(ix * b, (ix + 1) * b) for ix, b in zip(index, self.block_shape)
        )


class BlockedTensor:
    """A logical tensor stored padded-to-block, with block-grid metadata.

    ``data`` always has ``meta.padded_shape``; entries beyond ``meta.shape``
    are zero (ops that are not padding-invariant must mask — see
    ``netsdb_tpu.ops``).
    """

    def __init__(self, data: jax.Array, meta: BlockMeta):
        if tuple(data.shape) != meta.padded_shape:
            raise ValueError(
                f"data shape {tuple(data.shape)} != padded {meta.padded_shape}"
            )
        self.data = data
        self.meta = meta

    # --- construction -------------------------------------------------
    @staticmethod
    def from_dense(
        dense: Union[np.ndarray, jax.Array],
        block_shape: Shape,
        dtype: Optional[jnp.dtype] = None,
    ) -> "BlockedTensor":
        """Pad a dense array up to whole blocks (zeros in the ragged margin)."""
        dense = jnp.asarray(dense, dtype=dtype)
        meta = BlockMeta(tuple(dense.shape), tuple(block_shape))
        if meta.is_padded:
            pad = [(0, p - s) for s, p in zip(meta.shape, meta.padded_shape)]
            dense = jnp.pad(dense, pad)
        return BlockedTensor(dense, meta)

    @staticmethod
    def zeros(shape: Shape, block_shape: Shape, dtype=jnp.float32) -> "BlockedTensor":
        meta = BlockMeta(tuple(shape), tuple(block_shape))
        return BlockedTensor(jnp.zeros(meta.padded_shape, dtype=dtype), meta)

    @staticmethod
    def from_blocks(
        blocks: dict, shape: Shape, block_shape: Shape, dtype=jnp.float32
    ) -> "BlockedTensor":
        """Assemble from a {block_index: array} dict — the ingest path that
        mirrors sending a ``Vector<Handle<FFMatrixBlock>>`` (reference
        ``src/FF/headers/FFMatrixUtil.h`` load path). Ragged edge blocks may
        be passed unpadded; they are zero-padded into place."""
        meta = BlockMeta(tuple(shape), tuple(block_shape))
        out = np.zeros(meta.padded_shape, dtype=dtype)
        for index, arr in blocks.items():
            index = tuple(index)
            sl = meta.block_slice(index)
            arr = np.asarray(arr)
            dst = tuple(
                slice(s.start, s.start + d) for s, d in zip(sl, arr.shape)
            )
            out[dst] = arr
        return BlockedTensor(jnp.asarray(out), meta)

    # --- access -------------------------------------------------------
    @property
    def shape(self) -> Shape:
        return self.meta.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def grid(self) -> Shape:
        return self.meta.grid

    def block(self, *index: int) -> jax.Array:
        """One padded block — analogue of pulling one ``FFMatrixBlock``."""
        return self.data[self.meta.block_slice(index)]

    def blocks(self):
        """Iterate ``(index, block)`` pairs in row-major block order."""
        for flat in range(self.meta.num_blocks):
            index, rem = [], flat
            for g in reversed(self.meta.grid):
                index.append(rem % g)
                rem //= g
            index = tuple(reversed(index))
            yield index, self.block(*index)

    def to_dense(self) -> jax.Array:
        """Strip padding back to the logical shape."""
        if not self.meta.is_padded:
            return self.data
        return self.data[tuple(slice(0, s) for s in self.meta.shape)]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """1.0 inside the logical extent, 0.0 in the padded margin."""
        m = jnp.ones((), dtype=dtype)
        for dim, (s, p) in enumerate(zip(self.meta.shape, self.meta.padded_shape)):
            idx = jnp.arange(p)
            dim_mask = (idx < s).astype(dtype)
            bshape = [1] * self.meta.rank
            bshape[dim] = p
            m = m * dim_mask.reshape(bshape)
        return jnp.broadcast_to(m, self.meta.padded_shape)

    def astype(self, dtype) -> "BlockedTensor":
        return BlockedTensor(self.data.astype(dtype), self.meta)

    def with_data(self, data: jax.Array) -> "BlockedTensor":
        return BlockedTensor(data, self.meta)

    def reblock(self, block_shape: Shape) -> "BlockedTensor":
        """Change block granularity (re-pad as needed)."""
        return BlockedTensor.from_dense(self.to_dense(), block_shape)

    def __repr__(self) -> str:
        return (
            f"BlockedTensor(shape={self.meta.shape}, block={self.meta.block_shape}, "
            f"grid={self.meta.grid}, dtype={self.dtype})"
        )


def _bt_flatten(t: BlockedTensor):
    return (t.data,), t.meta


def _bt_unflatten(meta: BlockMeta, children):
    (data,) = children
    # Inside transforms children may be tracers/None; skip shape validation.
    obj = object.__new__(BlockedTensor)
    obj.data = data
    obj.meta = meta
    return obj


jax.tree_util.register_pytree_node(BlockedTensor, _bt_flatten, _bt_unflatten)
