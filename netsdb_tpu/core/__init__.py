from netsdb_tpu.core.blocked import BlockedTensor, BlockMeta

__all__ = ["BlockedTensor", "BlockMeta"]
