"""LSH index over weight-block signatures — sub-quadratic near-dup
detection across a model zoo.

The reference's offline dedup tooling builds an LSH index so
near-duplicate block discovery across N models is not O(N²) pairwise
(``model-inference/deduplication/indexing/deduplicator.py``,
``indexer.py``). Round 1 shipped exact + quantized fingerprints only
(``dedup/detector.py``) — right for two models, wrong shape for a zoo.

TPU-native design: signatures are random-hyperplane bits (SimHash) —
``sign(blocks @ R)`` — computed for EVERY block of a model in ONE
device matmul (the MXU does the hashing), then banded on the host:
b bands of r bits each; two blocks collide if any band matches, so for
similarity s the detection probability is 1-(1-s^r)^b (the standard
S-curve). Candidate pairs are then verified by signature Hamming
distance (and can be confirmed bit-exactly via detector fingerprints).
"""

from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor

BlockRef = Tuple[str, tuple]  # (model name, block index)


def _projection(n_features: int, n_bits: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n_features, n_bits)).astype(np.float32)


_proj_cache: Dict[Tuple[int, int, int], object] = {}


def _device_projection(n_features: int, n_bits: int, seed: int):
    """The projection matrix is tens of MB at weight-block sizes;
    cache it ON DEVICE so indexing N models uploads it once, not N
    times (over a tunnel that upload dominates everything else)."""
    key = (n_features, n_bits, seed)
    if key not in _proj_cache:
        import jax.numpy as jnp

        _proj_cache[key] = jnp.asarray(_projection(n_features, n_bits,
                                                   seed))
    return _proj_cache[key]


def _sign_bits(f, p):
    import jax
    import jax.numpy as jnp

    return (jax.lax.dot_general(
        f, p, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) >= 0)


_sign_bits_jit = None


def block_signatures(tensor: BlockedTensor, n_bits: int = 128,
                     seed: int = 0) -> Tuple[List[tuple], np.ndarray]:
    """All block signatures of one tensor in one device matmul:
    (block indices, (n_blocks, n_bits) uint8 bit matrix). The jitted
    kernel is module-level so indexing N same-shaped models compiles
    once, not N times."""
    global _sign_bits_jit
    import jax
    import jax.numpy as jnp

    if _sign_bits_jit is None:
        _sign_bits_jit = jax.jit(_sign_bits)
    idxs, blocks = zip(*list(tensor.blocks()))
    flat = jnp.stack([b.reshape(-1) for b in blocks])  # (n, elems)
    proj = _device_projection(flat.shape[1], n_bits, seed)
    bits = _sign_bits_jit(flat, proj)
    return list(idxs), np.asarray(bits).astype(np.uint8)


class LSHIndex:
    """Banded SimHash index over block signatures.

    ``n_bits`` must equal ``bands * rows_per_band``. Defaults (128 bits,
    16 bands of 8) put the S-curve knee near cosine ~0.95 — fine-tuned
    weight drift collides, unrelated weights don't."""

    def __init__(self, n_bits: int = 128, bands: int = 16, seed: int = 0):
        if n_bits % bands:
            raise ValueError(f"bands {bands} must divide n_bits {n_bits}")
        self.n_bits = n_bits
        self.bands = bands
        self.rows = n_bits // bands
        self.seed = seed
        self._buckets: Dict[Tuple[int, bytes], List[BlockRef]] = \
            collections.defaultdict(list)
        self._sigs: Dict[BlockRef, np.ndarray] = {}

    # --------------------------------------------------------- build
    def _band_keys(self, sig: np.ndarray) -> Iterable[Tuple[int, bytes]]:
        for b in range(self.bands):
            yield b, sig[b * self.rows:(b + 1) * self.rows].tobytes()

    def add_model(self, name: str, tensor: BlockedTensor) -> int:
        """Index every block; returns the number of blocks added."""
        idxs, sigs = block_signatures(tensor, self.n_bits, self.seed)
        for idx, sig in zip(idxs, sigs):
            ref = (name, idx)
            self._sigs[ref] = sig
            for key in self._band_keys(sig):
                self._buckets[key].append(ref)
        return len(idxs)

    # --------------------------------------------------------- query
    def candidates(self, ref: BlockRef) -> List[BlockRef]:
        """Blocks sharing >=1 band with ``ref`` (excluding itself) —
        the sub-quadratic candidate set."""
        sig = self._sigs[ref]
        out = []
        seen = {ref}
        for key in self._band_keys(sig):
            for other in self._buckets.get(key, ()):
                if other not in seen:
                    seen.add(other)
                    out.append(other)
        return out

    def hamming(self, a: BlockRef, b: BlockRef) -> int:
        return int(np.count_nonzero(self._sigs[a] != self._sigs[b]))

    # buckets up to this size are verified all-pairs; above it, each
    # member is checked against the bucket anchor only. The anchor
    # heuristic can miss a true pair whose bucket is anchored by an
    # unrelated hash collision — recovered only if the pair shares
    # another band's bucket — so small buckets (the common case, and
    # where a single collision distorts most) pay the exact quadratic
    # price, bounded at C(8,2)=28 checks.
    _EXACT_BUCKET_MAX = 8

    def near_duplicate_groups(self, max_hamming: Optional[int] = None
                              ) -> List[List[BlockRef]]:
        """Union-find over verified candidate pairs → groups of
        near-duplicate blocks across all indexed models. Work is
        O(candidate pairs), not O(n²): all-pairs inside small buckets,
        anchor-vs-rest in large ones (see ``_EXACT_BUCKET_MAX`` for the
        recall tradeoff of the anchor heuristic)."""
        if max_hamming is None:
            max_hamming = self.rows  # one band's worth of disagreement
        parent: Dict[BlockRef, BlockRef] = {r: r for r in self._sigs}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        self.verified_pairs = 0
        checked = set()  # each candidate pair verified once, however
        # many band buckets it shares (the reference deduplicator's
        # candidate-pair semantics)
        for refs in self._buckets.values():
            if len(refs) < 2:
                continue
            if len(refs) <= self._EXACT_BUCKET_MAX:
                pairs = ((refs[i], refs[j])
                         for i in range(len(refs))
                         for j in range(i + 1, len(refs)))
            else:
                anchor = refs[0]
                pairs = ((anchor, other) for other in refs[1:])
            for a, b in pairs:
                key = (a, b) if a <= b else (b, a)
                if key in checked:
                    continue
                checked.add(key)
                self.verified_pairs += 1
                if self.hamming(a, b) <= max_hamming:
                    ra, rb = find(a), find(b)
                    if ra != rb:
                        parent[rb] = ra
        groups = collections.defaultdict(list)
        for r in self._sigs:
            groups[find(r)].append(r)
        return [sorted(g) for g in groups.values() if len(g) > 1]

    def stats(self) -> Dict[str, int]:
        sizes = [len(v) for v in self._buckets.values()]
        return {"blocks": len(self._sigs),
                "buckets": len(self._buckets),
                "max_bucket": max(sizes, default=0)}


def dedup_model_zoo(models: Dict[str, BlockedTensor],
                    n_bits: int = 128, bands: int = 16,
                    max_hamming: Optional[int] = None,
                    seed: int = 0) -> Dict[str, object]:
    """Index a whole zoo and return near-duplicate block groups plus
    the pairwise-work saving — the reference's offline deduplicator
    run, sub-quadratic."""
    index = LSHIndex(n_bits, bands, seed)
    for name, t in models.items():
        index.add_model(name, t)
    groups = index.near_duplicate_groups(max_hamming)
    n = len(index._sigs)
    total_pairs = n * (n - 1) // 2
    return {"groups": groups, "index_stats": index.stats(),
            "verified_pairs": index.verified_pairs,
            "all_pairs": total_pairs,
            "pair_work_fraction": (index.verified_pairs / total_pairs
                                   if total_pairs else 0.0)}


def bench_lsh_zoo(n_models: int = 100, blocks_per_model: int = 8,
                  block: int = 256, n_families: int = 10,
                  noise: float = 1e-4, seed: int = 0
                  ) -> Dict[str, object]:
    """100 synthetic model variants (n_families base models, each with
    near-duplicate fine-tuned copies) indexed + grouped, with measured
    build and probe time — the model-zoo scale test."""
    import time

    rng = np.random.default_rng(seed)
    bases = [rng.standard_normal((blocks_per_model * block, block)
                                 ).astype(np.float32)
             for _ in range(n_families)]
    models = {}
    truth = {}
    for i in range(n_models):
        fam = i % n_families
        dense = bases[fam] + noise * rng.standard_normal(
            bases[fam].shape).astype(np.float32)
        models[f"model{i}"] = BlockedTensor.from_dense(dense,
                                                       (block, block))
        truth[f"model{i}"] = fam

    t0 = time.perf_counter()
    index = LSHIndex()
    for name, t in models.items():
        index.add_model(name, t)
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    groups = index.near_duplicate_groups()
    probe_s = time.perf_counter() - t0

    # grading: every group must be family-pure, and each (family, block
    # position) should unite all its variants
    pure = all(len({truth[name] for name, _ in g}) == 1 for g in groups)
    n = len(index._sigs)
    return {"models": n_models, "blocks": n,
            "build_s": round(build_s, 3), "probe_s": round(probe_s, 3),
            "groups": len(groups), "groups_family_pure": pure,
            "verified_pairs": index.verified_pairs,
            "all_pairs": n * (n - 1) // 2,
            "index_stats": index.stats()}
