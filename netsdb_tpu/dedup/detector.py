"""Model-weight deduplication — the reference's tensor-dedup subsystem.

The reference shares identical tensor blocks across models at the
storage level: ``TensorBlockIndex`` maps distinct blocks, private sets
iterate pages physically owned by a shared set
(``src/deduplication/headers/TensorBlockIndex.h:36``,
``SharedTensorBlockSet.h:25``), and offline Python tooling detects
duplicates (pairwise/LSH, ``model-inference/deduplication/indexing``)
and packs distinct blocks into pages greedily
(``model-inference/deduplication/page-packing``).

Here detection fingerprints blocks by content hash — exact for
bit-identical blocks, optionally on quantized values so near-identical
fine-tuned weights dedup too — and storage sharing reuses the set
store's alias mechanism (``SetStore.add_shared_mapping``).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.storage.store import SetIdentifier


def _fingerprint(block: np.ndarray, quantize: Optional[float]) -> str:
    if quantize:
        block = np.round(block / quantize).astype(np.int64)
    return hashlib.sha256(np.ascontiguousarray(block).tobytes()).hexdigest()


def block_fingerprints(tensor: BlockedTensor,
                       quantize: Optional[float] = None) -> Dict[tuple, str]:
    """{block index: content hash} — the TensorBlockIndex of one tensor."""
    return {idx: _fingerprint(np.asarray(blk), quantize)
            for idx, blk in tensor.blocks()}


def find_shared_blocks(client, sets: Sequence[Tuple[str, str]],
                       quantize: Optional[float] = None) -> Dict[str, List[Tuple[str, tuple]]]:
    """Across the given (db, set) weight sets, group block locations by
    fingerprint. Returns {hash: [(set_key, block_index), ...]} restricted
    to hashes appearing in ≥2 locations (the dedup opportunities)."""
    table: Dict[str, List[Tuple[str, tuple]]] = {}
    for db, set_name in sets:
        t = client.get_tensor(db, set_name)
        for idx, h in block_fingerprints(t, quantize).items():
            table.setdefault(h, []).append((f"{db}:{set_name}", idx))
    return {h: locs for h, locs in table.items() if len(locs) > 1}


def dedup_weight_sets(client, private_db: str, private_set: str,
                      shared_db: str, shared_set: str,
                      quantize: Optional[float] = None) -> Dict:
    """If two weight sets are fully identical (all blocks match), alias
    the private set onto the shared one — the addSharedMapping client
    flow (``src/mainClient/headers/PDBClient.h:113-138``). Returns the
    block mapping (or partial-overlap report when not fully dedupable)."""
    a = client.get_tensor(private_db, private_set)
    b = client.get_tensor(shared_db, shared_set)
    fa = block_fingerprints(a, quantize)
    fb = block_fingerprints(b, quantize)
    matches = {idx: idx for idx in fa if idx in fb and fa[idx] == fb[idx]}
    report = {"total_blocks": len(fa), "matching_blocks": len(matches),
              "aliased": False}
    if len(matches) == len(fa) and a.meta == b.meta:
        client.add_shared_mapping(private_db, private_set,
                                  shared_db, shared_set,
                                  mapping={str(k): str(v)
                                           for k, v in matches.items()})
        report["aliased"] = True
    return report


def pack_blocks_into_pages(block_sizes: Dict[str, int], page_size: int,
                           groups: Optional[List[List[str]]] = None
                           ) -> List[List[str]]:
    """Greedy page packing of distinct blocks (reference
    ``page-packing`` greedy algorithm): blocks that are shared by the
    same model group are co-located first, then first-fit-decreasing
    into ``page_size`` bins. Returns pages as lists of block keys."""
    pages: List[List[str]] = []
    page_used: List[int] = []

    def fit(keys: List[str]):
        for k in sorted(keys, key=lambda k: -block_sizes[k]):
            size = block_sizes[k]
            if size > page_size:
                raise ValueError(f"block {k} ({size}) exceeds page size")
            for i, used in enumerate(page_used):
                if used + size <= page_size:
                    pages[i].append(k)
                    page_used[i] += size
                    break
            else:
                pages.append([k])
                page_used.append(size)

    seen = set()
    for group in (groups or []):
        fit([k for k in group if k in block_sizes and k not in seen])
        seen.update(group)
    fit([k for k in block_sizes if k not in seen])
    return pages


def bin_pack_tensors(tensors: Dict[str, List[str]], blocks_per_page: int
                     ) -> Tuple[List[List[str]], Dict[str, List[int]]]:
    """Tensor-aware bin packing — the reference's "Greedy-2" page
    packer (``model-inference/deduplication/page-packing/algorithms/
    PagePacking.py::bin_pack_greedy`` + ``findMinBinsMaxCover``): the
    objective is not just few pages overall but few pages PER TENSOR,
    so loading any one model touches a minimal page set even when its
    blocks are shared with other models.

    ``tensors``: name → list of block ids (shared blocks appear in
    several tensors). ``blocks_per_page``: page capacity in blocks (the
    reference's ``l``). Returns ``(pages, mapping)`` where ``pages`` is
    a list of block-id lists and ``mapping[tensor]`` the sorted page
    indices that cover all its blocks.

    Strategy (same shape as the reference's): seed with the largest
    tensor, its blocks ordered by global frequency; then for each next
    tensor (size-descending) cover as much as possible from existing
    pages (max-cover reuse), pack only the uncovered remainder into new
    pages."""
    if blocks_per_page <= 0:
        raise ValueError("blocks_per_page must be positive")
    freq: Dict[str, int] = {}
    for blocks in tensors.values():
        for b in set(blocks):
            freq[b] = freq.get(b, 0) + 1

    pages: List[List[str]] = []
    where: Dict[str, int] = {}  # block id → page index
    mapping: Dict[str, List[int]] = {}

    def pack_new(blocks: List[str]) -> List[int]:
        """Append blocks (frequency-ordered) onto the last non-full
        page, then fresh pages."""
        used = []
        for b in sorted(blocks, key=lambda b: -freq[b]):
            if pages and len(pages[-1]) < blocks_per_page:
                pages[-1].append(b)
                where[b] = len(pages) - 1
            else:
                pages.append([b])
                where[b] = len(pages) - 1
            used.append(where[b])
        return used

    for name in sorted(tensors, key=lambda n: -len(tensors[n])):
        blocks = list(dict.fromkeys(tensors[name]))  # dedup, keep order
        covered = [b for b in blocks if b in where]
        fresh = [b for b in blocks if b not in where]
        page_ids = {where[b] for b in covered}
        page_ids.update(pack_new(fresh))
        mapping[name] = sorted(page_ids)
    return pages, mapping
