"""Device-resident shared block pool — model dedup in HBM.

The reference's serve-time dedup stores one physical copy of pages that
several model sets share (``src/deduplication/headers/
SharedTensorBlockSet.h:25``) and points private sets at them via
``addSharedPage``/``addSharedMapping`` (``src/mainClient/headers/
PDBClient.h:113-138``). Round 2 covered full-set aliasing
(``detector.dedup_weight_sets``); this module covers the finer and more
common case — two *fine-tuned variants* share MOST blocks — at the
HBM level:

- The LSH index (:mod:`netsdb_tpu.dedup.lsh`) groups near-duplicate
  blocks across all candidate models sub-quadratically; only blocks
  inside a group are byte-compared (LSH's job: blocks in no group are
  unique without any exact hashing).
- Exactly-equal blocks collapse to ONE slot in a stacked device pool
  array ``(P, bh, bw)``; each model keeps an int32 slot grid.
- A :class:`PooledTensor` stored in a set assembles back to its
  ``BlockedTensor`` on access (one device gather + reshape), and the
  assembly is CACHED on the PooledTensor: consecutive jobs reading the
  same pooled model reuse one dense copy instead of re-gathering
  (``assembly_count`` pins this in tests). The cache is dropped under
  store memory pressure (``SetStore._maybe_evict`` calls
  ``drop_pool_caches`` before spilling anything) and by ``drop_cache``
  — steady-state HBM then returns to the pool once plus slot grids,
  which is what the reference's shared pages buy. (The alternative —
  tracing pool+slots into every consumer jit — would avoid the dense
  copy entirely but couple every consumer's signature to pooling; not
  done.)

Only bit-identical blocks share a slot: assembly is exact, so inference
for every pooled model is unchanged to the bit.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.core.blocked import BlockedTensor, BlockMeta


class BlockPool:
    """Unique blocks of one (block_shape, dtype) class, stacked on
    device — the SharedTensorBlockSet."""

    def __init__(self, blocks: jax.Array, num_refs: int,
                 total_blocks: int):
        self.blocks = blocks  # (P, bh, bw)
        self.num_refs = num_refs
        self.total_blocks = total_blocks

    @property
    def nbytes(self) -> int:
        return int(self.blocks.nbytes)


class PooledTensor:
    """A model tensor materialized as slots into a shared BlockPool.

    Stored in a SetStore set in place of its BlockedTensor; the store
    assembles on access (``SetStore.get_items``), so every consumer —
    executor scans, serve handlers, checkpoints — sees an ordinary
    BlockedTensor while resident HBM holds only the pool + slot grid."""

    def __init__(self, pool: BlockPool, slots: np.ndarray, meta: BlockMeta):
        self.pool = pool
        self.slots = np.asarray(slots, np.int32)  # (gh, gw)
        self.meta = meta
        self._cache: Optional[BlockedTensor] = None
        self.assembly_count = 0  # gathers actually performed (tests pin
        # that consecutive reads don't re-gather)

    def assemble(self) -> BlockedTensor:
        if self._cache is not None:
            return self._cache
        self.assembly_count += 1
        gh, gw = self.slots.shape
        bh, bw = self.meta.block_shape
        picked = jnp.take(self.pool.blocks,
                          jnp.asarray(self.slots.reshape(-1)), axis=0)
        dense = picked.reshape(gh, gw, bh, bw).transpose(0, 2, 1, 3
                                                        ).reshape(gh * bh,
                                                                  gw * bw)
        self._cache = BlockedTensor(dense, self.meta)
        return self._cache

    def drop_cache(self) -> int:
        """Release the cached assembly (memory-pressure hook); returns
        the bytes released. Steady-state HBM falls back to pool+slots."""
        if self._cache is None:
            return 0
        released = int(self._cache.data.nbytes)
        self._cache = None
        return released

    @property
    def nbytes_resident(self) -> int:
        """Bytes this tensor alone pins (its slot grid). The shared
        pool's bytes are accounted at the STORE level — once per live
        pool, however many sets reference it, robust to any one set
        being removed/overwritten/spilled (``SetStore.live_pool_bytes``)."""
        return int(self.slots.nbytes)

    def __reduce__(self):
        # spill/checkpoint: persist as the full tensor (dedup is an
        # HBM-residency optimization, not a wire/disk format)
        t = self.assemble()
        return (_rebuild_blocked, (np.asarray(t.data), t.meta.shape,
                                   t.meta.block_shape))


def _rebuild_blocked(data, shape, block_shape):
    return BlockedTensor(jnp.asarray(data), BlockMeta(tuple(shape),
                                                      tuple(block_shape)))


def pool_models(tensors: Dict[str, BlockedTensor],
                bands: int = 16, n_bits: int = 128,
                seed: int = 0) -> Tuple[Dict[str, PooledTensor], Dict]:
    """Build one shared pool over the given model tensors.

    LSH groups candidate near-duplicate blocks; byte-exact members of a
    group share a slot. Returns ({name: PooledTensor}, report). All
    tensors must share block_shape and dtype (one pool class — the
    caller partitions by class)."""
    from netsdb_tpu.dedup.lsh import LSHIndex

    metas = {n: t.meta for n, t in tensors.items()}
    shapes = {(m.block_shape, str(tensors[n].dtype))
              for n, m in metas.items()}
    if len(shapes) > 1:
        raise ValueError(f"pool_models needs one block class; got {shapes}")

    index = LSHIndex(n_bits=n_bits, bands=bands, seed=seed)
    for name, t in tensors.items():
        index.add_model(name, t)
    groups = index.near_duplicate_groups()
    grouped_refs = {r for g in groups for r in g}
    group_of = {}
    for gi, g in enumerate(groups):
        for r in g:
            group_of[r] = gi

    # host copies once per model for hashing/stacking
    host: Dict[str, np.ndarray] = {}
    for name, t in tensors.items():
        gh, gw = t.meta.grid
        bh, bw = t.meta.block_shape
        host[name] = np.asarray(t.data).reshape(gh, bh, gw, bw
                                                ).transpose(0, 2, 1, 3)

    slot_of: Dict[object, int] = {}  # hash key → slot
    stacked: List[np.ndarray] = []
    slots: Dict[str, np.ndarray] = {}
    shared_hits = 0
    total = 0
    unique_seq = 0  # distinct key per ungrouped block (never shared)
    for name, t in tensors.items():
        gh, gw = t.meta.grid
        grid = np.zeros((gh, gw), np.int32)
        for i in range(gh):
            for j in range(gw):
                total += 1
                blk = host[name][i, j]
                ref = (name, (i, j))  # LSHIndex BlockRef convention
                if ref in grouped_refs:
                    # candidate near-dup: byte-exact key within its LSH
                    # group decides sharing
                    key = (group_of[ref],
                           hashlib.blake2b(blk.tobytes(),
                                           digest_size=16).digest())
                else:
                    key = ("u", unique_seq)  # unique, never shared
                    unique_seq += 1
                slot = slot_of.get(key)
                if slot is None:
                    slot = len(stacked)
                    stacked.append(blk)
                    slot_of[key] = slot
                else:
                    shared_hits += 1
                grid[i, j] = slot
        slots[name] = grid

    pool = BlockPool(jnp.asarray(np.stack(stacked)), num_refs=total,
                     total_blocks=total)
    pooled = {name: PooledTensor(pool, slots[name], metas[name])
              for name in tensors}
    bytes_before = sum(int(np.prod(m.padded_shape))
                       * tensors[n].data.dtype.itemsize
                       for n, m in metas.items())
    report = {
        "models": len(tensors),
        "total_blocks": total,
        "unique_blocks": len(stacked),
        "shared_block_refs": shared_hits,
        "lsh_groups": len(groups),
        "verified_pairs": index.verified_pairs,
        "hbm_bytes_before": bytes_before,
        "hbm_bytes_pooled": pool.nbytes,
        "hbm_savings_pct": round(100 * (1 - pool.nbytes
                                        / max(bytes_before, 1)), 1),
    }
    return pooled, report
