from netsdb_tpu.dedup.detector import (
    block_fingerprints,
    dedup_weight_sets,
    find_shared_blocks,
    pack_blocks_into_pages,
)

__all__ = ["block_fingerprints", "find_shared_blocks", "dedup_weight_sets",
           "pack_blocks_into_pages"]
