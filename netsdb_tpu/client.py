"""Client facade — the ``PDBClient`` equivalent.

The reference's ``PDBClient`` aggregates CatalogClient, DispatcherClient,
DistributedStorageManagerClient and QueryClient behind one object
(``src/mainClient/headers/PDBClient.h:28-295``): createDatabase/createSet/
sendData/registerType/executeComputations/getSetIterator. In
single-controller JAX there is no client⇄master RPC hop — the "client" IS
the controller — so this facade talks directly to the catalog, the set
store, and the query executor. The API surface is kept deliberately close
so every reference test driver has a line-for-line analogue.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from netsdb_tpu.catalog.catalog import Catalog
from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.storage.store import SetIdentifier, SetStore


def _ident(db: str, set_name: str) -> SetIdentifier:
    return SetIdentifier(db, set_name)


def table_info(table) -> Dict[str, Any]:
    """The analyze-set summary for one resident ColumnTable — the ONE
    place its shape is defined (Client.analyze_set and the daemon's
    ANALYZE_SET handler both build it here, so they cannot diverge)."""
    from netsdb_tpu.relational.stats import analyze_table

    return {"stats": dict(analyze_table(table)),
            "dicts": dict(table.dicts), "num_rows": table.num_rows}


class Client:
    """Facade over catalog + storage + execution.

    Mirrors ``PDBClient`` (reference ``src/mainClient/headers/PDBClient.h``):

    ===========================  =======================================
    reference                    here
    ===========================  =======================================
    createDatabase               :meth:`create_database`
    createSet<T>(db,set,...)     :meth:`create_set`
    sendData<T>(pair, vector)    :meth:`send_data` / :meth:`send_matrix`
    flushData                    :meth:`flush_data`
    registerType(.so)            :meth:`register_type` (Python entry point)
    executeComputations          :meth:`execute_computations`
    getSetIterator<T>            :meth:`get_set_iterator`
    removeSet / clearSet         :meth:`remove_set` / :meth:`clear_set`
    addSharedMapping (dedup)     :meth:`add_shared_mapping`
    ===========================  =======================================
    """

    def __new__(cls, config: Configuration = DEFAULT_CONFIG,
                catalog_path: Optional[str] = None,
                address: Optional[str] = None,
                token: Optional[str] = None,
                replicas=None):
        if address is not None:
            # thin RPC mode — talk to a resident daemon instead of
            # owning the store (reference: PDBClient always works this
            # way; here the in-process library is the default and
            # ``Client(address="host:port")`` is the served form).
            # ``replicas``: other daemon addresses holding the same
            # data — enables client-side hedged reads (tail latency;
            # see RemoteClient).
            from netsdb_tpu.serve.client import RemoteClient

            return RemoteClient(address, token=token, replicas=replicas)
        return super().__new__(cls)

    def __init__(self, config: Configuration = DEFAULT_CONFIG,
                 catalog_path: Optional[str] = None,
                 address: Optional[str] = None,
                 token: Optional[str] = None,
                 replicas=None):
        del address, token, replicas  # consumed by __new__ (RemoteClient)
        self.config = config
        config.ensure_dirs()
        from netsdb_tpu.config import enable_compilation_cache

        enable_compilation_cache(config)  # PreCompiledWorkload analogue
        self.catalog = Catalog(catalog_path or ":memory:")
        self.store = SetStore(config)
        # mesh of the most recent placement applied via create_set — the
        # cluster this controller is currently distributing over (the
        # reference's ResourceManager serverlist role)
        self._mesh = None
        self._advisor = None  # Lachesis-lite (set_placement_advisor)
        self._advisor_key = "default"
        self._advisor_arm = None  # arm applied by this session's DDL

    @property
    def mesh(self):
        """The device mesh of the last placement-carrying ``create_set``
        (None while every set is single-device)."""
        return self._mesh

    # --- self-learning placement (Lachesis) ---------------------------
    def set_placement_advisor(self, advisor, key: str = "default") -> None:
        """Install a :class:`~netsdb_tpu.learning.advisor.PlacementAdvisor`
        the DDL and query paths consult — the reference's self-learning
        hooks at set creation and scheduling
        (``QuerySchedulerServer.cc:246-330``, dispatcher placement
        optimizers). ``key`` names the workload whose measured history
        drives set placement: ``create_set`` picks block shape from the
        best-known arm for ``key``, and ``execute_computations`` runs
        each job under the advisor's choice for that job, recording
        elapsed time back to the history DB — the reference's
        first-run-slow, later-runs-fast loop (documentation.md:5-10)."""
        self._advisor = advisor
        self._advisor_key = key

    # --- DDL ----------------------------------------------------------
    def create_database(self, db: str) -> None:
        self.catalog.create_database(db)

    def create_set(
        self,
        db: str,
        set_name: str,
        type_name: str = "tensor",
        persistence: str = "transient",
        eviction: str = "lru",
        partition_lambda: Optional[str] = None,
        placement=None,
        storage: str = "memory",
    ) -> SetIdentifier:
        """``partition_lambda`` mirrors createSet-with-dispatch-computation
        (reference ``PDBClient.h:79-103``): a named key function the
        dispatcher/placement layer may use to route data.

        ``storage="paged"`` backs the set with the shared page arena
        instead of RAM: ingest pages the relation in row-chunks, and
        Computation DAGs over the set run STREAMED — the executor folds
        each fold-bearing stage over the page stream under the arena's
        pool cap (the reference's PageScanner-fed out-of-core execution,
        ``src/storage/headers/PageScanner.h:25-34``). Composes with
        ``placement``: streamed chunks are mesh-sharded per chunk.
        Durability: the arena's spill files are capacity, not
        durability — a paged set persists via ``flush``/``flush_data``
        (snapshot of the materialized relation; reload re-ingests into
        the arena, coming back paged).

        ``placement`` (:class:`~netsdb_tpu.parallel.placement.Placement`
        or its ``to_meta`` dict) declares the set's mesh sharding — the
        createSet-time PartitionPolicy (``PartitionPolicy.h:27-50``):
        every tensor/table ingested into the set is placed with it, and
        query jits over the set inherit the sharding, so XLA distributes
        the job the way the reference scheduler broadcast stages to all
        workers."""
        if not self.catalog.database_exists(db):
            raise KeyError(f"database {db!r} does not exist; create_database first")
        if storage not in ("memory", "paged"):
            # validate BEFORE the catalog write — a late store-side
            # rejection would leave a dangling catalog row
            raise ValueError(f"storage must be 'memory' or 'paged', "
                             f"got {storage!r}")
        from netsdb_tpu.parallel.placement import Placement

        if isinstance(placement, dict):
            placement = Placement.from_meta(placement)
        meta: Dict[str, Any] = {}
        if partition_lambda:
            meta["partition_lambda"] = partition_lambda
        arm = (self._advisor.choose(self._advisor_key)
               if self._advisor is not None else None)
        if placement is None and arm is not None:
            # an advisor arm may carry a sharding decision (the DRL /
            # rule-based optimizers choose *distribution*, not just
            # page size — Lachesis' decision variable on TPU): specs
            # values may be Placement objects keyed by set role
            spec = arm.specs.get("placement") or arm.specs.get(set_name)
            if isinstance(spec, Placement):
                placement = spec
                # the arm's placement is the configuration actually in
                # force for this DDL — stash it so job timings record
                # against it (same discipline as the block-shape arms
                # below) and audit the decision
                self._advisor_arm = arm
                self._advisor.db.record(
                    f"{self._advisor_key}:decisions",
                    plan_key=f"set:{db}.{set_name}", elapsed_s=0.0,
                    config_label=arm.label)
        if placement is not None:
            meta["sharding"] = placement.to_meta()
            self._mesh = placement.mesh()
            # placement-history row: the sharding actually applied by
            # DDL, auditable by the advisor/judge (the reference logs
            # its placement decisions to the self-learning DB)
            from netsdb_tpu.learning.history import get_history_db

            get_history_db().record(
                f"{db}.{set_name}:placement", plan_key=f"set:{db}.{set_name}",
                elapsed_s=0.0, config_label=placement.label())
        if arm is not None and type_name == "tensor" \
                and "block" in arm.specs:
            # live Lachesis decision: the chosen placement (block shape
            # = the reference's page-size knob) lands in the catalog and
            # the history DB, and send_matrix defaults to it. Decision
            # rows live under "<key>:decisions" so they audit the live
            # choices without polluting the reward means.
            # Stashed ONLY when the arm actually decided something for
            # THIS set: a model's later sets consulting the advisor
            # must not overwrite the arm a placement decision applied
            # (job timings would then record against the wrong arm)
            meta["placement"] = arm.label
            meta["block_shape"] = list(arm.specs["block"])
            self._advisor_arm = arm  # the placement actually in force
            self._advisor.db.record(f"{self._advisor_key}:decisions",
                                    plan_key=f"set:{db}.{set_name}",
                                    elapsed_s=0.0,
                                    config_label=arm.label)
        if storage != "memory":
            meta["storage"] = storage
        self.catalog.create_set(db, set_name, type_name, meta, persistence)
        ident = _ident(db, set_name)
        self.store.create_set(ident, persistence=persistence, eviction=eviction,
                              placement=placement, storage=storage)
        return ident

    def remove_set(self, db: str, set_name: str) -> None:
        self.catalog.remove_set(db, set_name)
        self.store.remove_set(_ident(db, set_name))

    def clear_set(self, db: str, set_name: str) -> None:
        self.store.clear_set(_ident(db, set_name))

    def set_exists(self, db: str, set_name: str) -> bool:
        return self.catalog.set_exists(db, set_name)

    # --- types --------------------------------------------------------
    def register_type(self, type_name: str, entry_point: str,
                      source: Optional[str] = None,
                      ship_module: bool = False) -> None:
        """Register an op/model implementation by dotted import path
        (ref registerType / VTableMap dynamic loading,
        ``src/objectModel/headers/VTableMap.h:36-80``).

        ``source`` ships the module's code through the catalog so a
        daemon that has never installed it can still execute the type —
        the reference replicating user-type .so binaries
        (``PDBCatalog.h:45-50``). ``ship_module=True`` reads the source
        off the locally-importable module instead."""
        if ship_module and source is None:
            from netsdb_tpu.catalog.catalog import read_module_source

            source = read_module_source(entry_point)
        self.catalog.register_type(type_name, entry_point, source=source)

    # --- data path ----------------------------------------------------
    def send_data(self, db: str, set_name: str, items: Sequence[Any]) -> None:
        """Sets created with ``type_name="objects"`` columnarize at
        ingest: records flow through ``autojoin.table_from_objects``
        into ONE dictionary-encoded ColumnTable (string keys become
        device codes), so ``Join(on=...)`` DAGs over the set run on the
        device engine — the reference's dispatcher building typed pages
        from raw records (``JoinPairArray.h:122`` re-priced). All other
        sets store items as-is (the host-record path)."""
        ident = _ident(db, set_name)
        info = self.catalog.get_set(db, set_name)
        if info is not None and info.get("type") == "objects":
            if not items:
                return  # empty batch: same no-op as the object path
            from netsdb_tpu.relational.autojoin import (concat_tables,
                                                        table_from_objects)
            from netsdb_tpu.relational.table import ColumnTable

            new = table_from_objects(list(items))

            def append(existing_items):
                tables = [i for i in existing_items
                          if isinstance(i, ColumnTable)]
                # append = device concat + dictionary remap; runs
                # atomically under the store lock (update_set), so
                # concurrent senders cannot lose each other's batch
                return [concat_tables(tables[0], new) if tables else new]

            self.store.update_set(ident, append)
            return
        self.store.add_data(ident, list(items))

    def send_matrix(
        self,
        db: str,
        set_name: str,
        dense: Union[np.ndarray, "Any"],
        block_shape: Optional[Tuple[int, int]] = None,
        dtype=None,
    ) -> BlockedTensor:
        """Load a dense matrix as one blocked tensor into a set — the
        analogue of ``FFMatrixUtil::load_matrix`` generating a
        ``Vector<Handle<FFMatrixBlock>>`` and sendData'ing it.

        Block shape resolution: explicit argument > the set's
        advisor-chosen placement (catalog meta, written by
        ``create_set`` under a PlacementAdvisor) > config default.

        A ``storage="paged"`` set takes the HOST array straight into
        the arena — no BlockedTensor, nothing device-resident (the
        whole point is matrices larger than HBM; consume them with
        :meth:`paged_matmul`). Returns None in that case."""
        ident = _ident(db, set_name)
        if self.store.storage_of(ident) == "paged":
            dense_np = np.ascontiguousarray(
                np.asarray(dense, dtype or np.float32))
            self.store.add_data(ident, [dense_np])
            cat = self.catalog.get_set(db, set_name)
            if cat is not None:
                cat["meta"].update(shape=list(dense_np.shape),
                                   dtype=str(dense_np.dtype))
                self.catalog.update_set_meta(db, set_name, cat["meta"])
            return None
        if block_shape is None:
            info = self.catalog.get_set(db, set_name)
            placed = (info or {}).get("meta", {}).get("block_shape")
            if placed:
                block_shape = tuple(placed)
        block_shape = block_shape or self.config.default_block_shape
        t = BlockedTensor.from_dense(dense, block_shape, dtype=dtype)
        ident = _ident(db, set_name)
        self.store.put_tensor(ident, t)
        cat = self.catalog.get_set(db, set_name)
        if cat is not None:
            cat["meta"].update(
                shape=list(t.shape), block_shape=list(t.meta.block_shape),
                dtype=str(t.dtype),
            )
            self.catalog.update_set_meta(db, set_name, cat["meta"])
        return t

    def send_table(self, db: str, set_name: str, rows_or_table,
                   date_cols: Sequence[str] = (),
                   append: bool = False) -> "Any":
        """Ingest a relation as ONE ColumnTable (dictionary-encoding
        string columns on the way in — weak-typed rows become device
        columns, the reference's dispatcher page-building role). If the
        set carries a placement, the store shards the table's rows over
        the mesh (PartitionPolicy applied at ingest,
        ``src/dispatcher/headers/PartitionPolicy.h:27-50``).

        ``append=True`` adds the batch to the stored relation instead
        of replacing it — the reference's addData continuously
        appending pages (``StorageAddData``): paged sets write
        additional arena pages, memory sets concat with dictionary
        remap; both atomic under the store lock."""
        from netsdb_tpu.relational.table import ColumnTable

        table = (rows_or_table if isinstance(rows_or_table, ColumnTable)
                 else ColumnTable.from_rows(list(rows_or_table), date_cols))
        ident = _ident(db, set_name)
        if append:
            self.store.append_table(ident, table)
            cat = self.catalog.get_set(db, set_name)
            if cat is not None:  # catalog reflects the TOTAL after append
                info = self.analyze_set(db, set_name)
                cat["meta"].update(num_rows=info["num_rows"],
                                   columns=sorted(table.cols))
                self.catalog.update_set_meta(db, set_name, cat["meta"])
            return table
        self.store.clear_set(ident)
        self.store.add_data(ident, [table])
        cat = self.catalog.get_set(db, set_name)
        if cat is not None:
            cat["meta"].update(num_rows=table.num_rows,
                               columns=sorted(table.cols))
            self.catalog.update_set_meta(db, set_name, cat["meta"])
        return table

    def get_table(self, db: str, set_name: str):
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.relational.table import ColumnTable

        items = self.store.get_items(_ident(db, set_name))
        tables = [i for i in items if isinstance(i, ColumnTable)]
        if not tables:
            paged = [i for i in items if isinstance(i, PagedColumns)]
            if len(paged) == 1:
                # compatibility materialization — HOST-side assembly
                # (numpy columns, nothing touches HBM): the set was
                # paged because it does not fit; queries should go
                # through the DAG path, which folds over the stream
                return paged[0].to_host_table()
        if len(tables) != 1:
            raise ValueError(
                f"set {db}:{set_name} holds {len(tables)} tables; expected 1")
        return tables[0]

    def analyze_set(self, db: str, set_name: str) -> Dict[str, Any]:
        """Planner statistics for a stored relation WITHOUT
        materializing it: resident tables analyze in place (cached);
        paged sets return their ingest-time stats. This is the
        reference's collect-stats-where-the-data-lives surface
        (``StorageCollectStats``, ``PangeaStorageServer.h:48``) — the
        DAG builders consume these summaries instead of pulling tables
        (``relational/dag.py``)."""
        from netsdb_tpu.relational.outofcore import PagedColumns

        items = self.store.get_items(_ident(db, set_name))
        if len(items) == 1 and isinstance(items[0], PagedColumns):
            pc = items[0]
            return {"stats": dict(pc.stats), "dicts": dict(pc.dicts),
                    "num_rows": pc.num_rows}
        return table_info(self.get_table(db, set_name))

    def get_tensor(self, db: str, set_name: str) -> BlockedTensor:
        return self.store.get_tensor(_ident(db, set_name))

    def paged_matmul(self, db: str, set_name: str, rhs) -> np.ndarray:
        """``stored @ rhs`` with the stored matrix STREAMED page by
        page through the device — the larger-than-HBM weight pattern
        as a set property: ``create_set(storage="paged")`` +
        ``send_matrix`` pages the matrix into the arena, and only one
        page + ``rhs`` are device-resident at a time."""
        return self.store.paged_matmul(_ident(db, set_name), rhs)

    def get_set_iterator(self, db: str, set_name: str) -> Iterator[Any]:
        return self.store.scan(_ident(db, set_name))

    def flush_data(self) -> None:
        """Durably flush all persistent sets (ref flushData →
        StorageCleanup broadcast, ``PDBClient.h:141``). Paged sets
        snapshot as their materialized relation and re-ingest into the
        arena on reload (``SetStore.flush``)."""
        for ident in self.store.list_sets():
            info = self.catalog.get_set(ident.db, ident.set)
            if info and info.get("persistence") == "persistent":
                self.store.flush(ident)

    def dedup_resident(self, sets: Sequence[Tuple[str, str]],
                       bands: int = 16, seed: int = 0) -> Dict[str, Any]:
        """Dedup device-resident model weight sets at block level: LSH
        groups near-duplicate blocks across the sets, byte-identical
        group members collapse into one shared device pool, and each
        set keeps a slot grid (``dedup/pool.py``) — fine-tuned variants
        share HBM the way the reference's models share physical pages
        (``SharedTensorBlockSet.h:25``, ``PDBClient.h:113-138``).
        Inference is bit-unchanged; returns the pooling report. Sets
        are partitioned by (block_shape, dtype) class; classes with one
        member still pool (dedup within a single model's repeated
        blocks)."""
        from netsdb_tpu.dedup.pool import pool_models

        tensors: Dict[str, BlockedTensor] = {}
        for db, set_name in sets:
            tensors[f"{db}:{set_name}"] = self.get_tensor(db, set_name)
        by_class: Dict[Any, Dict[str, BlockedTensor]] = {}
        for name, t in tensors.items():
            by_class.setdefault((t.meta.block_shape, str(t.dtype)),
                                {})[name] = t
        total: Dict[str, Any] = {"classes": len(by_class), "models": 0,
                                 "total_blocks": 0, "unique_blocks": 0,
                                 "shared_block_refs": 0,
                                 "hbm_bytes_before": 0,
                                 "hbm_bytes_pooled": 0}
        for cls, group in by_class.items():
            pooled, report = pool_models(group, bands=bands, seed=seed)
            for name, pt in pooled.items():
                db, set_name = name.split(":", 1)
                self.store.set_pooled(_ident(db, set_name), pt)
            for k in ("models", "total_blocks", "unique_blocks",
                      "shared_block_refs", "hbm_bytes_before",
                      "hbm_bytes_pooled"):
                total[k] += report[k]
        total["hbm_savings_pct"] = round(
            100 * (1 - total["hbm_bytes_pooled"]
                   / max(total["hbm_bytes_before"], 1)), 1)
        return total

    # --- dedup (ref PDBClient::addSharedPage/addSharedMapping) --------
    def add_shared_mapping(
        self, private_db: str, private_set: str, shared_db: str, shared_set: str,
        mapping: Optional[Dict] = None,
    ) -> None:
        self.store.add_shared_mapping(
            _ident(private_db, private_set), _ident(shared_db, shared_set), mapping
        )

    # --- query execution ----------------------------------------------
    def execute_computations(self, *sinks, job_name: str = "job",
                             materialize: bool = True,
                             explain: bool = False):
        """Plan + run a Computation DAG — ``QueryClient::executeComputations``
        (reference ``src/queries/headers/QueryClient.h:160-224``) without the
        client→master RPC hop. ``sinks`` are Write computations from
        :mod:`netsdb_tpu.plan.computations`.

        ``explain=True`` is the in-process EXPLAIN ANALYZE: the
        executor records every plan node's wall/device time, rows and
        cache/compile counters (``obs/operators.py``) and the return
        becomes ``(results, operators_tree)``.

        With a placement advisor installed, the job's elapsed time is
        recorded against the arm whose placement this session's DDL
        actually APPLIED (``create_set`` stashes it) — never against an
        arm that was merely chosen, so per-arm means measure real
        physical configurations (the scheduler-side self-learning hook,
        ``QuerySchedulerServer.cc:246-330``)."""
        from netsdb_tpu import obs
        from netsdb_tpu.plan.executor import execute_computations

        def run():
            if self._advisor is not None and self._advisor_arm is not None:
                from netsdb_tpu.learning.history import set_config_label

                set_config_label(self._advisor_arm.label)
                try:
                    return execute_computations(self, list(sinks),
                                                job_name=job_name,
                                                materialize=materialize)
                finally:
                    set_config_label("")  # no stale-arm tagging
            return execute_computations(self, list(sinks),
                                        job_name=job_name,
                                        materialize=materialize)

        if not explain:
            return run()
        with obs.operators.explain_capture() as cap:
            results = run()
        return results, cap.get("operators")

    # --- stats --------------------------------------------------------
    def collect_stats(self) -> Dict[str, Any]:
        """Per-set storage stats (ref StorageCollectStats → ``Statistics``
        used by the cost-based planner)."""
        return {
            str(i): self.store.set_stats(i) for i in self.store.list_sets()
        }
