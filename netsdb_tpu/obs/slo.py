"""SLO / health engine — declarative objectives over the registry.

PR 5 made the runtime *measurable* (central :class:`~netsdb_tpu.obs.
metrics.MetricsRegistry`, query-scoped traces); this module makes it
*judgeable*: a small set of declarative objectives (availability, p99
request latency, device-cache hit rate, staging wait fraction) is
evaluated against the registry with **multi-window burn rates** — the
standard SRE alerting form (a short window catches a fast burn, a long
window a slow leak; both must agree before a breach is real).

The registry holds CUMULATIVE counters; objectives need RATES. The
engine therefore keeps a bounded ring of timestamped readings (one
reading = the few raw values the objectives reference) and computes
each window's value from the delta between the newest reading and the
oldest reading inside that window. Until a window has history, it
falls back to the all-time value — a fresh daemon reports its lifetime
ratio rather than "no data".

Objective kinds:

* ``ratio_min`` — good/total ≥ target (availability, devcache hit
  rate). Burn rate = (1 − ratio) / (1 − target): 1.0 means the error
  budget burns exactly at the sustainable pace, >1 means faster.
* ``quantile_max`` — a registry histogram's q-quantile ≤ target (p99
  request latency). Quantiles come from the histogram's bounded sample
  ring (recent by construction), so they are already "windowed";
  burn rate = value / target.
* ``rate_max`` — a histogram's TOTAL-seconds delta per wall second ≤
  target (staging wait fraction: how much of real time the consumers
  spent blocked on device uploads). Burn rate = value / target.

Everything is stdlib-only and monotonic-clocked (the obs layer
inherits the serve clock discipline — static-checked). Breaches emit
structured events into a bounded ring and tick
``slo.breaches``/``slo.recoveries`` registry counters; the serve
``HEALTH`` frame ships :meth:`SLOEngine.evaluate` plus the events, and
a leader merges follower sections exactly like COLLECT_STATS.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock

#: default evaluation windows (seconds): fast-burn, slow-burn
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 600.0)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective. ``good``/``total``/``hist`` name
    registry instruments; which are read depends on ``kind`` (module
    docstring). ``quantile`` applies to ``quantile_max`` only."""

    name: str
    kind: str  # "ratio_min" | "quantile_max" | "rate_max"
    target: float
    description: str = ""
    good: Optional[str] = None   # counter name (ratio_min numerator)
    total: Optional[str] = None  # counter name (ratio_min denominator)
    hist: Optional[str] = None   # histogram name (quantile_max/rate_max)
    quantile: float = 0.99

    def __post_init__(self):
        if self.kind not in ("ratio_min", "quantile_max", "rate_max"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if self.kind == "ratio_min" and not (self.good and self.total):
            raise ValueError(f"{self.name}: ratio_min needs good+total")
        if self.kind in ("quantile_max", "rate_max") and not self.hist:
            raise ValueError(f"{self.name}: {self.kind} needs hist")


def default_objectives() -> List[Objective]:
    """The shipped objective set — the signals the ROADMAP scheduler
    will admit against. Counters/histograms referenced here are all
    maintained by the serve/staging/devcache layers."""
    return [
        Objective(
            name="availability", kind="ratio_min", target=0.999,
            good="serve.requests_ok", total="serve.requests",
            description="fraction of dispatched frames answered "
                        "without an ERR"),
        Objective(
            name="request_p99_s", kind="quantile_max", target=2.0,
            hist="serve.request_s", quantile=0.99,
            description="p99 server-side frame dispatch latency "
                        "(time-to-first-frame for streams)"),
        Objective(
            name="devcache_hit_rate", kind="ratio_min", target=0.5,
            good="devcache.hits", total="devcache.lookups",
            description="device block cache hit rate (warm serving)"),
        Objective(
            name="staging_wait_fraction", kind="rate_max", target=0.25,
            hist="staging.wait_s",
            description="fraction of wall time consumers spent blocked "
                        "on staged host->device uploads"),
    ]


class SLOEngine:
    """Evaluates objectives over one registry with windowed burn
    rates. One per daemon (the ServeController owns it); tests build
    private ones over private registries.

    ``evaluate()`` is cheap (a registry read + a few arithmetic ops)
    and takes a reading as a side effect, so a daemon polled by
    HEALTH frames accumulates exactly the history it needs — no
    background thread."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None,
                 objectives: Optional[List[Objective]] = None,
                 windows: Tuple[float, ...] = DEFAULT_WINDOWS,
                 max_readings: int = 256, max_events: int = 128,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        self.windows = tuple(sorted(windows))
        self._clock = clock
        self._mu = TrackedLock("SLOEngine._mu")
        # (t, {counter_name: value, "ht:"+hist: total_seconds})
        self._readings: "deque[Tuple[float, Dict[str, float]]]" = \
            deque(maxlen=max(int(max_readings), 2))
        self._events: "deque[Dict[str, Any]]" = \
            deque(maxlen=max(int(max_events), 1))
        self._breached: Dict[str, bool] = {}
        self._take_reading()  # the t0 baseline every window deltas from

    # --- readings -----------------------------------------------------
    def _counter_names(self) -> List[str]:
        names = []
        for o in self.objectives:
            if o.kind == "ratio_min":
                names.extend((o.good, o.total))
        return names

    def _take_reading(self) -> Tuple[float, Dict[str, float]]:
        vals: Dict[str, float] = {}
        for name in self._counter_names():
            vals[name] = float(self.registry.counter(name).value)
        for o in self.objectives:
            if o.kind == "rate_max":
                vals[f"ht:{o.hist}"] = float(
                    self.registry.histogram(o.hist).total)
        reading = (self._clock(), vals)
        with self._mu:
            self._readings.append(reading)
        return reading

    def observe(self) -> None:
        """Take one timestamped reading (HEALTH polls call evaluate,
        which does this implicitly; call directly to densify)."""
        self._take_reading()

    # --- evaluation ---------------------------------------------------
    def _window_delta(self, now: float, window: float, key: str,
                      newest: Dict[str, float]
                      ) -> Optional[Tuple[float, float]]:
        """(delta_value, delta_seconds) between the newest reading and
        the OLDEST reading inside ``window``; None when no prior
        reading exists (caller falls back to all-time)."""
        with self._mu:
            base = None
            for t, vals in self._readings:
                if now - t <= window:
                    base = (t, vals)
                    break
            if base is None or now - base[0] <= 0:
                return None
        dv = newest.get(key, 0.0) - base[1].get(key, 0.0)
        return dv, now - base[0]

    def _eval_ratio(self, o: Objective, now: float,
                    newest: Dict[str, float]) -> Dict[str, Any]:
        """``value`` is the WORST window's ratio (what an operator
        wants to see first); ``breached`` requires EVERY window with
        data to sit below target — the multi-window agreement rule
        (module docstring): the short window alone flaps on bursts,
        the long window alone lags a real outage."""
        windows: Dict[str, Dict[str, Any]] = {}
        worst_burn = 0.0
        value = None
        agree: List[bool] = []
        for w in self.windows:
            dg = self._window_delta(now, w, o.good, newest)
            dt_ = self._window_delta(now, w, o.total, newest)
            if dg is None or dt_ is None or dt_[0] <= 0:
                # no traffic in the window (or no history): all-time
                tot = newest.get(o.total, 0.0)
                ratio = (newest.get(o.good, 0.0) / tot) if tot else None
                scope = "all-time"
            else:
                ratio = dg[0] / dt_[0]
                scope = "window"
            burn = None
            if ratio is not None:
                budget = max(1.0 - o.target, 1e-9)
                burn = max(0.0, (1.0 - ratio)) / budget
                worst_burn = max(worst_burn, burn)
                value = ratio if value is None else min(value, ratio)
                agree.append(ratio < o.target)
            windows[f"{int(w)}s"] = {"value": ratio, "burn_rate": burn,
                                     "scope": scope}
        breached = bool(agree) and all(agree)
        return {"value": value, "windows": windows,
                "worst_burn_rate": worst_burn if value is not None
                else None, "breached": breached}

    def _eval_quantile(self, o: Objective) -> Dict[str, Any]:
        h = self.registry.histogram(o.hist)
        q = h.quantile(o.quantile)
        burn = (q / o.target) if q is not None and o.target > 0 else None
        win = {"samples": {"value": q, "burn_rate": burn,
                           "scope": f"last-{h.sample_count}-samples"}}
        return {"value": q, "windows": win, "worst_burn_rate": burn,
                "breached": q is not None and q > o.target}

    def _eval_rate(self, o: Objective, now: float,
                   newest: Dict[str, float]) -> Dict[str, Any]:
        """Same agreement rule as :meth:`_eval_ratio`: ``value`` is
        the worst window's rate, ``breached`` only when every window
        with history exceeds target."""
        key = f"ht:{o.hist}"
        windows: Dict[str, Dict[str, Any]] = {}
        worst = None
        agree: List[bool] = []
        for w in self.windows:
            d = self._window_delta(now, w, key, newest)
            if d is None:
                windows[f"{int(w)}s"] = {"value": None, "burn_rate": None,
                                         "scope": "no-history"}
                continue
            rate = max(d[0], 0.0) / d[1]
            burn = (rate / o.target) if o.target > 0 else None
            worst = rate if worst is None else max(worst, rate)
            agree.append(rate > o.target)
            windows[f"{int(w)}s"] = {"value": rate, "burn_rate": burn,
                                     "scope": "window"}
        return {"value": worst, "windows": windows,
                "worst_burn_rate": (worst / o.target)
                if worst is not None and o.target > 0 else None,
                "breached": bool(agree) and all(agree)}

    def evaluate(self) -> List[Dict[str, Any]]:
        """Evaluate every objective (taking a fresh reading first).
        Msgpack-safe list, one dict per objective; breach TRANSITIONS
        emit structured events and tick registry counters."""
        now, newest = self._take_reading()
        out = []
        for o in self.objectives:
            if o.kind == "ratio_min":
                res = self._eval_ratio(o, now, newest)
            elif o.kind == "quantile_max":
                res = self._eval_quantile(o)
            else:
                res = self._eval_rate(o, now, newest)
            res.update(name=o.name, kind=o.kind, target=o.target,
                       description=o.description)
            self._transition(o, res)
            out.append(res)
        return out

    def breached_objectives(self, evaluate: bool = True) -> List[str]:
        """Names of objectives currently breached on ALL their windows
        (the multi-window agreement rule). ``evaluate=True`` takes a
        fresh evaluation first — the scheduler's load-shedding probe
        (serve/sched/feedback.py) must not depend on HEALTH polling
        cadence; ``False`` reads the last evaluation's state."""
        if evaluate:
            return [r["name"] for r in self.evaluate()
                    if r.get("breached")]
        with self._mu:
            return sorted(n for n, b in self._breached.items() if b)

    # --- events -------------------------------------------------------
    def _transition(self, o: Objective, res: Dict[str, Any]) -> None:
        breached = bool(res.get("breached"))
        with self._mu:
            was = self._breached.get(o.name, False)
            self._breached[o.name] = breached
            if breached == was:
                return
            from netsdb_tpu.utils.timing import wall_now

            self._events.append({
                "at": wall_now(),  # display timestamp (sanctioned)
                "objective": o.name,
                "event": "breach" if breached else "recovery",
                "value": res.get("value"),
                "target": o.target,
                "worst_burn_rate": res.get("worst_burn_rate")})
        self.registry.counter(
            "slo.breaches" if breached else "slo.recoveries").inc()

    def events(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._mu:
            evs = list(self._events)
        return evs if last is None else evs[-int(last):]
