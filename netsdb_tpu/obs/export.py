"""OpenMetrics / Prometheus text exposition of the registry.

``GET_METRICS format=openmetrics`` turns one registry snapshot (plus
leader-merged follower snapshots and the per-(client, set) attribution
ledger) into the Prometheus text format every scraper understands::

    # HELP netsdb_serve_requests_total frames dispatched ...
    # TYPE netsdb_serve_requests_total counter
    netsdb_serve_requests_total 1042
    netsdb_serve_requests_total{follower="127.0.0.1:9001"} 310
    netsdb_attrib_staged_bytes_total{client="tenant-a",set="d:lineitem"} 83886080

Rules this module enforces:

* **Stable names.** Every exported family maps 1:1 to a catalogued
  registry metric (:data:`CATALOG` — the machine-readable twin of
  ``docs/METRICS.md``; the static check in ``tests/test_static_checks
  .py`` keeps code ↔ catalog ↔ docs drift-free). A registry
  instrument NOT in the catalog is skipped and counted
  (``obs.export.uncatalogued``) — the exporter can never leak an
  unreviewed name into a scrape.
* **Typed exposition.** Counters export as ``*_total`` counter
  families; gauges as gauges; registry histograms as ``summary``
  families (``_sum``/``_count`` exact forever, ``quantile`` lines
  from the bounded sample ring).
* **Labels.** Follower sections ride a ``follower`` label; the
  attribution ledger exports per-``client``/``set`` sample lines under
  ``netsdb_attrib_*`` families — the multi-tenant view a Prometheus
  alert can group by.

:func:`parse_openmetrics` is the small in-repo grammar checker the
acceptance tests run over every scrape — names, label syntax, sample
types and float values all validated, so "parses under the Prometheus
text-format grammar" is a tested property, not a hope.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from netsdb_tpu.obs import metrics as _metrics

#: metric families of the ATTRIBUTION ledger (obs/attrib.py accounts
#: these per (client, scope); they are not registry instruments, so
#: they are catalogued here and in docs/METRICS.md explicitly)
ATTRIB_METRICS = (
    "requests", "staged_bytes", "staged_chunks", "devcache.hits",
    "devcache.misses", "devcache.installs", "devcache.partial_hits",
    "executor.chunks",
)


def _catalog() -> Dict[str, Tuple[str, str]]:
    """name → (type, help) for every exported metric. Built by a
    function (obs/ bans module-level dict literals — the static
    counter-discipline check); the docs twin is ``docs/METRICS.md``."""
    counters = (
        ("serve.requests", "workload frames dispatched (outcome time; "
                           "OBS frames excluded)"),
        ("serve.requests_ok", "workload frames answered without an ERR"),
        ("serve.idem.memory_hits", "idempotent retries answered from "
                                   "the in-memory reply cache"),
        ("serve.idem.persist_hits", "idempotent retries answered from "
                                    "the persisted sqlite cache"),
        ("serve.client.retries", "client-side request retries"),
        ("serve.client.hedges_issued", "hedged reads issued"),
        ("serve.client.hedges_won", "hedged reads won by the hedge"),
        ("serve.client.traces_shipped", "client trace profiles shipped "
                                        "via PUT_TRACE"),
        ("serve.client.trace_ship_failures", "PUT_TRACE ship failures "
                                             "(best-effort, counted)"),
        ("serve.client.trace_ship_dropped", "client trace profiles "
                                            "dropped on a full ship "
                                            "queue"),
        ("serve.client.placement_refreshes", "placement-map re-fetches "
                                             "after a stale-map "
                                             "rejection"),
        ("serve.client.routed_ingests", "logical ingests routed "
                                        "directly to owning shards"),
        ("serve.mirror_dropped", "queued mirror frames dropped by an "
                                 "abort-closed follower link"),
        ("ha.terms", "HA term adoptions (promotions plus higher-term "
                     "observations)"),
        ("ha.promotions", "follower-to-leader promotions won after the "
                          "election window"),
        ("ha.stragglers_rejected", "stale-term frames from a deposed "
                                   "leader rejected with a typed "
                                   "NotLeader"),
        ("mutlog.appended_bytes", "bytes appended to the durable "
                                  "mutation log (mirror frames, token "
                                  "aliases, handoff spill)"),
        ("shard.scatter_queries", "queries executed scatter-gather "
                                  "across the shard pool by this "
                                  "coordinator"),
        ("shard.subplans", "pushed subplans executed over this "
                           "daemon's local pages"),
        ("shard.partials_merged", "per-slot partial results merged by "
                                  "the coordinator (all-or-nothing)"),
        ("shard.shuffle_parts", "distributed-shuffle buckets received "
                                "from peer shards"),
        ("shard.shuffle_bytes", "bytes received over the distributed "
                                "shuffle (out-of-band v3 segments)"),
        ("shard.epoch_rejects", "frames rejected for a stale placement "
                                "epoch (typed PlacementStale)"),
        ("shard.handoff_batches", "ingest batches buffered for a "
                                  "degraded shard slot at the leader"),
        ("shard.handoff_drained", "buffered handoff batches shipped to "
                                  "a readmitted shard (its own pages "
                                  "only)"),
        ("shard.evictions", "shard daemons degraded out of the pool "
                            "(slots flip to handoff, epochs bump)"),
        ("shard.readmits", "shard daemons readmitted after a "
                           "shard-scoped resync"),
        ("shard.analyze_fanouts", "ANALYZE_SET requests fanned out "
                                  "over a partitioned set's slots and "
                                  "merged (rows sum, min/max envelope, "
                                  "dict union)"),
        ("models.deploys", "model-as-blocked-sets deployments over a "
                           "serving pool (weights mirrored to every "
                           "member)"),
        ("models.batches_scored", "scoring frames executed over the "
                                  "serving pool"),
        ("models.rows_scored", "batch rows scored over the serving "
                               "pool (the rows/s headline numerator)"),
        ("sched.feedback_reseeds", "lane weight/quota reseeds applied "
                                   "from the attribution + operator "
                                   "ledgers (sched_feedback)"),
        ("sched.shed_events", "heaviest-lane quota halvings applied "
                              "by SLO burn-rate load shedding "
                              "(sched_slo_shed)"),
        ("devcache.lookups", "device block cache lookups (hits+misses)"),
        ("devcache.hits", "device block cache hits"),
        ("devcache.misses", "device block cache misses"),
        ("devcache.installs", "complete runs installed into the device "
                              "cache"),
        ("devcache.evictions", "device cache LRU evictions"),
        ("devcache.invalidations", "device cache entries dropped by "
                                   "write-path invalidation"),
        ("devcache.partial_hits", "individual device-resident blocks "
                                  "served by range-stitched streams "
                                  "(partial-run caching)"),
        ("devcache.stitched_ranges", "contiguous cached ranges "
                                     "stitched into staged streams"),
        ("devcache.dirty_invalidations", "block entries dropped by "
                                         "dirty-RANGE invalidation "
                                         "(intersecting a written row "
                                         "range)"),
        ("summa.rounds", "SUMMA round programs dispatched over the "
                         "mesh (one per N-block batch)"),
        ("summa.panel_bcasts", "B panels broadcast over the mesh axis "
                               "by SUMMA steps"),
        ("summa.panel_bytes", "bytes moved by SUMMA panel broadcasts "
                              "(interconnect, not host transfers)"),
        ("summa.staged_bytes", "operand bytes staged host->device by "
                               "SUMMA runs (sum over participants; "
                               "~1/N of operand bytes per host)"),
        ("summa.grid_rounds", "2-d grid SUMMA round programs "
                              "dispatched (one per pr-block batch)"),
        ("summa.grid_steps", "dual-broadcast steps executed by 2-d "
                             "grid SUMMA rounds (pr*pc per round)"),
        ("summa.grid_panel_bcasts", "A and B slices broadcast over the "
                                    "grid axes (2 per grid step)"),
        ("summa.grid_staged_bytes", "operand bytes staged host->device "
                                    "by 2-d grid SUMMA runs (~1/(pr*pc) "
                                    "of each operand per device)"),
        ("reshard.plans", "collective-step reshard schedules planned"),
        ("reshard.steps", "collective steps executed by reshards "
                          "(all_gather / all_to_all / local_slice / "
                          "replace)"),
        ("reshard.blocks_moved", "device-resident blocks moved between "
                                 "layouts device-to-device (zero arena "
                                 "reads)"),
        ("reshard.bytes_moved", "bytes moved between layouts without a "
                                "host round-trip"),
        ("staging.chunks", "chunks staged host->device"),
        ("staging.bytes", "bytes staged host->device (accounted "
                          "streams)"),
        ("obs.traces.client", "completed client-origin query traces"),
        ("obs.traces.server", "completed server-origin query traces"),
        ("obs.traces.local", "completed local-origin query traces"),
        ("obs.traces.bench", "completed bench-origin query traces"),
        ("obs.qid_sampled_out", "requests that skipped tracing under "
                                "1-in-N qid sampling"),
        ("obs.slow_queries", "profiles persisted to the slowlog ring"),
        ("obs.slowlog_errors", "slowlog persistence failures (counted, "
                               "never fatal)"),
        ("obs.put_trace.merged", "PUT_TRACE sections merged into a "
                                 "ringed profile"),
        ("obs.put_trace.unmatched", "PUT_TRACE sections whose qid never "
                                    "ringed"),
        ("obs.operators_overflow", "operator-ledger rows folded into "
                                   "the overflow bucket"),
        ("obs.export.uncatalogued", "registry instruments skipped by "
                                    "the OpenMetrics exporter for "
                                    "missing a catalog entry"),
        ("attrib.overflow", "attribution rows folded into the overflow "
                            "bucket"),
        ("sched.admits", "jobs granted an admission slot by the query "
                         "scheduler"),
        ("sched.quota_rejects", "jobs refused because their lane's "
                                "queue quota was full (typed "
                                "LaneSaturated)"),
        ("sched.timeouts", "jobs refused after waiting out the "
                           "admission timeout (typed AdmissionFull)"),
        ("sched.aged_grants", "admissions granted by the "
                              "anti-starvation aging rule instead of "
                              "lane weights"),
        ("sched.coalesce_hits", "EXECUTE frames coalesced behind an "
                                "identical in-flight execution"),
        ("sched.coalesce_late_hits", "EXECUTE frames served from the "
                                     "completed-fingerprint retention "
                                     "window just after their leader "
                                     "finished"),
        ("sched.coalesce_failures", "coalesced waiters aborted by a "
                                    "failed or overlong leader "
                                    "(typed CoalesceAborted)"),
        ("sched.affinity_hits", "queries that waited behind a cold "
                                "hot-set installer and woke into the "
                                "warm device cache"),
        ("sched.affinity_installs", "cold-set installer executions "
                                    "registered by the affinity gate"),
        ("fusion.regions_formed", "fusion regions formed by the plan "
                                  "mapper (plan/fusion.py)"),
        ("fusion.nodes_fused", "plan nodes compiled inside a fusion "
                               "region"),
        ("fusion.fallbacks", "fusion regions abandoned at execution "
                             "time (non-jit-safe values) — the nodes "
                             "ran per-node instead"),
        ("fusion.cost_estimates", "per-node cost-model estimates "
                                  "computed by the fusion mapper"),
        ("fusion.splits", "fusion regions split at their cheapest "
                          "edge because the single-region staged-"
                          "bytes estimate exceeded "
                          "fusion_stage_budget_bytes"),
        ("fusion.distributed_regions", "fusion regions compiled "
                                       "across the scatter boundary "
                                       "(per-shard partial-fold "
                                       "programs + coordinator "
                                       "merge+finalize programs)"),
        ("slo.breaches", "SLO objective breach transitions"),
        ("slo.recoveries", "SLO objective recovery transitions"),
        ("analysis.violations", "runtime lock-order cycles detected "
                                "by the lockdep witness"),
        ("rebalance.moves", "shard slot moves committed by the live "
                            "rebalancer (epoch-bumped, "
                            "count-verified)"),
        ("rebalance.bytes_moved", "partition bytes shipped by "
                                  "committed rebalance moves"),
        ("rebalance.aborts", "rebalance moves unwound before their "
                             "epoch commit (peer death, count "
                             "mismatch, source shrank)"),
        ("rebalance.skew_checks", "skew-detector passes run on the "
                                  "sched-feedback / pool-health "
                                  "cadence"),
        ("rebalance.advisor_commits", "rebalance moves kept by the "
                                      "placement-advisor arm after a "
                                      "measured throughput win"),
        ("session.opened", "interactive decode sessions opened "
                           "(SESSION_OPEN accepted; idempotent "
                           "re-opens excluded)"),
        ("session.closed", "interactive decode sessions closed "
                           "(explicit SESSION_CLOSE; TTL expiry "
                           "counts under session.evicted)"),
        ("session.evicted", "per-session state entries evicted from "
                            "the device cache (TTL expiry or LRU "
                            "pressure; spilled to the arena first)"),
        ("session.decode_steps", "decode steps applied to session "
                                 "state (one per session per batch "
                                 "dispatch)"),
        ("session.batch_occupancy", "summed batch occupancy across "
                                    "decode dispatches (divide by "
                                    "batches for mean coalescing)"),
        ("session.budget_spills", "advanced state layers larger than "
                                  "the whole device-cache budget, "
                                  "written straight to the arena "
                                  "instead of resident"),
        ("session.spill_errors", "session state spill callbacks that "
                                 "failed (state copy missed, cache "
                                 "unharmed)"),
        ("session.spill_push_errors", "dirty-state pushes to the "
                                      "session's home daemon that "
                                      "failed (re-marked, retried "
                                      "next housekeeping tick)"),
    )
    gauges = (
        ("placement.epoch", "the placement map's global epoch (bumps "
                            "on every membership change and "
                            "committed slot move)"),
        ("analysis.lock_edges", "distinct lock-rank acquisition-order "
                                "edges observed by the witness"),
        ("analysis.callgraph_edges", "resolved call edges in the "
                                     "interprocedural lint rules' "
                                     "project call graph"),
        ("analysis.race_findings", "static shared-state race findings "
                                   "on the last lint run"),
        ("analysis.witness_uncovered_edges", "static lock-order edges "
                                             "the runtime witness has "
                                             "never exercised "
                                             "(untested concurrency)"),
        ("sched.queue_depth", "requests currently queued across all "
                              "scheduler lanes"),
        ("devcache.pinned_bytes", "bytes of head blocks currently "
                                  "pinned against LRU eviction "
                                  "(device_cache_pin_bytes)"),
        ("session.resident_bytes", "bytes of per-session decode state "
                                   "currently resident in the device "
                                   "cache"),
        ("dedup.page_bytes", "unique model weight-page bytes resident "
                             "after cross-model deduplication "
                             "(compare against the per-model "
                             "attribution sum)"),
    )
    hists = (
        ("sched.queue_wait_s", "seconds a job waited in its scheduler "
                               "lane before admission (the "
                               "retry_after_s hint's feed)"),
        ("serve.request_s", "server-side frame latency seconds "
                            "(time-to-first-frame for streams)"),
        ("serve.client.read_latency_s", "client-observed read latency "
                                        "seconds (the hedge trigger "
                                        "feed)"),
        ("staging.wait_s", "consumer seconds blocked on a staged "
                           "host->device upload"),
    )
    out: Dict[str, Tuple[str, str]] = {}
    for name, help_ in counters:
        out[name] = ("counter", help_)
    for name, help_ in gauges:
        out[name] = ("gauge", help_)
    for name, help_ in hists:
        out[name] = ("histogram", help_)
    for name in ATTRIB_METRICS:
        out[f"attrib.{name}"] = (
            "counter", f"per-(client, set) attributed {name}")
    return out


#: the machine-readable metric catalog (docs/METRICS.md is the twin)
CATALOG = _catalog()

_QUANTILES = (0.5, 0.95, 0.99)


def metric_name(raw: str, suffix: str = "") -> str:
    """Registry name → Prometheus family name: ``netsdb_`` prefix,
    dots/dashes to underscores, counter families get ``_total``."""
    return "netsdb_" + re.sub(r"[^a-zA-Z0-9_:]", "_", raw) + suffix


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\"", r"\"") \
        .replace("\n", r"\n")


def _labels(pairs: Dict[str, str]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(pairs.items()))
    return "{" + inner + "}"


def _fmt(v: Any) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Writer:
    """Accumulates one exposition: families declared once (# HELP/
    # TYPE), samples appended under them in declaration order."""

    def __init__(self):
        self._order: List[str] = []
        self._fams: Dict[str, Dict[str, Any]] = {}

    def family(self, fam: str, typ: str, help_: str) -> None:
        if fam not in self._fams:
            self._order.append(fam)
            self._fams[fam] = {"type": typ, "help": help_,
                               "samples": []}

    def sample(self, fam: str, name: str, labels: Dict[str, str],
               value: Any) -> None:
        self._fams[fam]["samples"].append(
            f"{name}{_labels(labels)} {_fmt(value)}")

    def render(self) -> str:
        lines: List[str] = []
        for fam in self._order:
            info = self._fams[fam]
            lines.append(f"# HELP {fam} {info['help']}")
            lines.append(f"# TYPE {fam} {info['type']}")
            lines.extend(info["samples"])
        return "\n".join(lines) + "\n"


def _emit_numeric(w: _Writer, snapshot: Dict[str, Any],
                  labels: Dict[str, str], skipped: List[str]) -> None:
    """Counters + gauges + histogram summaries of one registry
    snapshot (``MetricsRegistry.snapshot()`` shape) under ``labels``."""
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        cat = CATALOG.get(name)
        if cat is None or cat[0] != "counter":
            skipped.append(name)
            continue
        fam = metric_name(name, "_total")
        w.family(fam, "counter", cat[1])
        w.sample(fam, fam, labels, value)
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        cat = CATALOG.get(name)
        if cat is None or cat[0] != "gauge":
            skipped.append(name)
            continue
        fam = metric_name(name)
        w.family(fam, "gauge", cat[1])
        w.sample(fam, fam, labels, value)
    for name, h in sorted((snapshot.get("histograms") or {}).items()):
        cat = CATALOG.get(name)
        if cat is None or cat[0] != "histogram":
            skipped.append(name)
            continue
        fam = metric_name(name)
        w.family(fam, "summary", cat[1])
        for q in _QUANTILES:
            qv = h.get(f"p{int(q * 100)}")
            if qv is not None:
                w.sample(fam, fam, {**labels, "quantile": str(q)}, qv)
        w.sample(fam, fam + "_sum", labels, h.get("total") or 0.0)
        w.sample(fam, fam + "_count", labels, h.get("count") or 0)


def _emit_attribution(w: _Writer, attribution: Dict[str, Any],
                      labels: Dict[str, str],
                      skipped: List[str]) -> None:
    """The per-(client, set) ledger as labelled counter families."""
    for client, scopes in sorted((attribution or {}).items()):
        if not isinstance(scopes, dict):
            continue
        for scope, metrics in sorted(scopes.items()):
            for name, value in sorted((metrics or {}).items()):
                cat = CATALOG.get(f"attrib.{name}")
                if cat is None:
                    skipped.append(f"attrib.{name}")
                    continue
                fam = metric_name(f"attrib.{name}", "_total")
                w.family(fam, "counter", cat[1])
                w.sample(fam, fam,
                         {**labels, "client": client, "set": scope},
                         value)


def to_openmetrics(snapshot: Dict[str, Any],
                   followers: Optional[Dict[str, Dict[str, Any]]] = None
                   ) -> str:
    """One Prometheus text exposition from a local registry snapshot
    (``MetricsRegistry.snapshot()`` — the COLLECT_STATS "metrics"
    shape) plus optional follower snapshots (addr → same shape),
    merged under a ``follower`` label. Only catalogued names are
    emitted; skipped instruments tick ``obs.export.uncatalogued``."""
    w = _Writer()
    skipped: List[str] = []
    _emit_numeric(w, snapshot, {}, skipped)
    _emit_attribution(w, snapshot.get("attribution") or {}, {}, skipped)
    for addr, fsnap in sorted((followers or {}).items()):
        if not isinstance(fsnap, dict) or "error" in fsnap:
            continue
        labels = {"follower": str(addr)}
        _emit_numeric(w, fsnap, labels, skipped)
        _emit_attribution(w, fsnap.get("attribution") or {}, labels,
                          skipped)
    if skipped:
        _metrics.REGISTRY.counter("obs.export.uncatalogued").inc(
            len(skipped))
    return w.render()


# ---------------------------------------------------------------------
# the in-repo Prometheus text-format parser (the acceptance oracle)
# ---------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{.*\})?\s+"
    r"([+-]?(?:[0-9]+\.?[0-9]*|\.[0-9]+)(?:[eE][+-]?[0-9]+)?"
    r"|[+-]?Inf|NaN)"
    r"(?:\s+(-?[0-9]+))?$")
_TYPES = {"counter", "gauge", "summary", "histogram", "untyped"}
#: sample-name suffixes each family type may emit beyond the bare name
#: (dict() call, not a literal — the obs/ static check reserves
#: module-level dict literals for registry-counter vigilance)
_SUFFIXES = dict(summary=("_sum", "_count"),
                 histogram=("_sum", "_count", "_bucket"),
                 counter=(), gauge=(), untyped=())


def parse_openmetrics(text: str) -> Dict[str, Dict[str, Any]]:
    """Strict-enough Prometheus text-format parse: validates family
    declarations, metric/label naming, sample grammar and the
    type/suffix contract; raises ``ValueError`` (with line number) on
    any violation. Returns {family: {"type", "help", "samples":
    [(name, labels, value)]}} — what the acceptance tests assert
    over."""
    fams: Dict[str, Dict[str, Any]] = {}

    def fam_of(sample_name: str) -> Optional[str]:
        if sample_name in fams:
            return sample_name
        for fam, info in fams.items():
            if sample_name.startswith(fam) and \
                    sample_name[len(fam):] in _SUFFIXES[info["type"]]:
                return fam
        return None

    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            if not parts or not _NAME_RE.match(parts[0]):
                raise ValueError(f"line {i}: bad HELP name: {line!r}")
            fams.setdefault(parts[0], {"type": "untyped", "help": "",
                                       "samples": []})
            fams[parts[0]]["help"] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2 or not _NAME_RE.match(parts[0]):
                raise ValueError(f"line {i}: bad TYPE line: {line!r}")
            if parts[1] not in _TYPES:
                raise ValueError(f"line {i}: unknown type {parts[1]!r}")
            fams.setdefault(parts[0], {"type": parts[1], "help": "",
                                       "samples": []})
            fams[parts[0]]["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue  # free comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: bad sample line: {line!r}")
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if labelstr:
            body = labelstr[1:-1].rstrip(",")
            if body:
                consumed = 0
                for lm in _LABEL_RE.finditer(body):
                    labels[lm.group(1)] = lm.group(2)
                    consumed = lm.end()
                rest = body[consumed:].strip(", ")
                if rest:
                    raise ValueError(
                        f"line {i}: bad label syntax near {rest!r}")
        fam = fam_of(name)
        if fam is None:
            raise ValueError(
                f"line {i}: sample {name!r} has no declared family "
                f"(or an illegal suffix for its family type)")
        info = fams[fam]
        if info["type"] == "counter" and name == fam \
                and not fam.endswith("_total"):
            raise ValueError(
                f"line {i}: counter family {fam!r} must end in _total")
        fams[fam]["samples"].append((name, labels, float(value)))
    return fams
