"""Continuous telemetry — a bounded ring of registry snapshots.

The registry (``obs/metrics.py``) answers "how much, ever"; the
ROADMAP scheduler and any external monitor need "how fast, lately".
:class:`TelemetryHistory` snapshots the registry's NUMERIC surface on
a fixed cadence (a daemon thread, monotonic-clocked, started/stopped
with the serve controller) into a ring of at most ``capacity``
readings, then derives RATES between any two readings: QPS, staged
MB/s, chunk rates, devcache hit-rate trend — the deltas ``cli obs
--top`` refreshes from and the ``GET_METRICS`` frame ships.

Boundedness is a hard contract (the acceptance criterion): one
reading holds only counters + gauges + per-histogram ``(count,
total)`` pairs — no samples, no collector sections — so resident cost
is exactly ``ring length × snapshot size`` and a year-long daemon
holds the same few hundred KB as a fresh one. ``stop()`` sets the
event and JOINS the thread; the controller calls it on shutdown so no
snapshot thread outlives its daemon (the staging leak-registry
lesson).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock

#: counter/histogram names with a human meaning as a rate — the
#: derived section `deltas()` computes (name → (feed, kind, scale)):
#: plain counters divide by dt; "ratio" derives delta(good)/delta(total)
_DERIVED = (
    ("qps", "serve.requests", "rate", 1.0),
    ("staged_mb_s", "staging.bytes", "rate", 1e-6),
    ("staged_chunks_s", "staging.chunks", "rate", 1.0),
    ("devcache_hit_rate", ("devcache.hits", "devcache.lookups"),
     "ratio", 1.0),
    ("availability", ("serve.requests_ok", "serve.requests"),
     "ratio", 1.0),
    ("devcache_installs_s", "devcache.installs", "rate", 1.0),
)


class TelemetryHistory:
    """Bounded snapshot ring + rate derivation over one registry."""

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None,
                 capacity: int = 120, interval_s: float = 5.0,
                 clock=time.monotonic):
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.capacity = max(int(capacity), 2)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._mu = TrackedLock("TelemetryHistory._mu")
        self._ring: "deque[Tuple[float, Dict[str, Any]]]" = \
            deque(maxlen=self.capacity)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- readings -----------------------------------------------------
    def _reading(self) -> Dict[str, Any]:
        """One numeric-only registry snapshot
        (:meth:`MetricsRegistry.numeric_snapshot`) — deliberately no
        samples and no collector sections, so a reading's size is
        bounded by the instrument count, not by traffic."""
        return self.registry.numeric_snapshot()

    def observe(self) -> None:
        """Take one timestamped reading now (the thread's tick; tests
        call it directly to densify without waiting)."""
        reading = (self._clock(), self._reading())
        with self._mu:
            self._ring.append(reading)

    # --- lifecycle ----------------------------------------------------
    def start(self) -> None:
        """Start the snapshot thread (idempotent; ``interval_s <= 0``
        disables — readings then come only from explicit
        :meth:`observe` calls, e.g. per GET_METRICS poll)."""
        if self._thread is not None or self.interval_s <= 0:
            return
        self.observe()  # the t0 baseline every delta anchors on
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="netsdb-obs-history")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.observe()

    def stop(self, timeout_s: float = 10.0) -> None:
        """Stop + JOIN the snapshot thread (idempotent) — the daemon
        shutdown hook; after this no history thread is alive."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # --- rates --------------------------------------------------------
    def _bracket(self, window_s: Optional[float]
                 ) -> Optional[Tuple[Tuple[float, Dict[str, Any]],
                                     Tuple[float, Dict[str, Any]]]]:
        """(oldest-in-window, newest) readings; None without ≥2."""
        with self._mu:
            if len(self._ring) < 2:
                return None
            newest = self._ring[-1]
            if window_s is None:
                return self._ring[0], newest
            base = None
            for t, snap in self._ring:
                if newest[0] - t <= window_s:
                    base = (t, snap)
                    break
            if base is None or newest[0] - base[0] <= 0:
                return None
            return base, newest

    def deltas(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """Rates between the newest reading and the oldest one inside
        ``window_s`` (or the whole ring): per-counter ``<name>``/s for
        every counter that moved, plus the named derived signals
        (``qps``, ``staged_mb_s``, hit-rate trend, ...). Empty dict
        until two readings exist."""
        br = self._bracket(window_s)
        if br is None:
            return {}
        (t0, old), (t1, new) = br
        dt = t1 - t0
        if dt <= 0:
            return {}
        rates: Dict[str, float] = {}
        for name, v in new["counters"].items():
            dv = v - old["counters"].get(name, 0)
            if dv:
                rates[name] = dv / dt
        out: Dict[str, Any] = {"dt_s": dt, "rates": rates}
        derived: Dict[str, Optional[float]] = {}
        for label, feed, kind, scale in _DERIVED:
            if kind == "rate":
                dv = (new["counters"].get(feed, 0)
                      - old["counters"].get(feed, 0))
                derived[label] = (dv / dt) * scale
            else:  # ratio of two counter deltas over the window
                good, total = feed
                dg = (new["counters"].get(good, 0)
                      - old["counters"].get(good, 0))
                dt_ = (new["counters"].get(total, 0)
                       - old["counters"].get(total, 0))
                derived[label] = (dg / dt_) if dt_ > 0 else None
        out["derived"] = derived
        return out

    def summary(self) -> Dict[str, Any]:
        with self._mu:
            n = len(self._ring)
            span = (self._ring[-1][0] - self._ring[0][0]) if n >= 2 \
                else 0.0
        return {"readings": n, "capacity": self.capacity,
                "interval_s": self.interval_s, "span_s": span,
                "running": self.running}
