"""Central metrics registry — one home for every runtime counter.

The reference's observability kit is scattered the same way ours had
grown: ``CacheStats`` counters on the buffer pool, ``-DPROFILING``
printf spans per pipeline phase, per-subsystem ad-hoc totals. This
module is the consolidation point: ONE process-wide
:class:`MetricsRegistry` holding typed instruments —

* :class:`Counter` — monotonic totals (cache hits, retries, chunks);
* :class:`Gauge` — last-set values (live threads, resident bytes);
* :class:`Histogram` — bounded-sample distributions with exact
  ``count``/``total``/``max`` and approximate p50/p95/p99 from a
  reservoir (a long-lived daemon must never grow per-sample state
  without bound — the StageTimer lesson);
* **collectors** — lazy callables merged into :meth:`snapshot`, the
  absorption mechanism for pre-existing stats surfaces
  (``plan.executor.compile_stats``, the staging leak registry, the
  global :class:`~netsdb_tpu.utils.profiling.StageTimer`) so their
  current accessors keep working while the registry reports the same
  numbers.

Everything here is stdlib-only (no jax, no numpy): the registry is
imported by the wire client, which is deliberately JAX-free.

Instruments are cheap enough for per-chunk hot paths: one lock-guarded
integer add. The registry is process-wide by design — per-store or
per-connection state keeps living on its object (``DeviceBlockCache.
stats()``, ``RemoteClient.hedges_won``); the registry aggregates
across them. ``snapshot()`` returns plain ints/floats/strings/dicts —
msgpack-safe, so the serve ``COLLECT_STATS`` frame ships it verbatim.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

#: default per-histogram sample bound (config.obs_hist_samples
#: overrides at construction sites that have a Configuration)
DEFAULT_HIST_SAMPLES = 512


class Counter:
    """Monotonic counter. ``inc`` is the only mutator."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0

    def inc(self, n: int = 1) -> None:
        with self._mu:
            self._v += n

    @property
    def value(self) -> int:
        with self._mu:
            return self._v


class Gauge:
    """Last-written value (float)."""

    __slots__ = ("_mu", "_v")

    def __init__(self):
        self._mu = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    def add(self, dv: float) -> None:
        with self._mu:
            self._v += float(dv)

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class Histogram:
    """Bounded-memory distribution: exact ``count``/``total``/``min``/
    ``max`` forever, quantiles from the last ``max_samples``
    observations (a ring, so the distribution tracks RECENT behavior —
    what a hedge trigger or an SLO readout wants — while a year-long
    daemon holds a fixed few KB per histogram)."""

    __slots__ = ("_mu", "_ring", "_cap", "_idx", "count", "total",
                 "_min", "_max")

    def __init__(self, max_samples: int = DEFAULT_HIST_SAMPLES):
        self._mu = threading.Lock()
        self._cap = max(int(max_samples), 8)
        self._ring: List[float] = []
        self._idx = 0
        self.count = 0
        self.total = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._mu:
            self.count += 1
            self.total += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self._cap

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile over the retained samples (None when
        empty). Nearest-rank over a sorted copy — the ring is small by
        construction."""
        with self._mu:
            if not self._ring:
                return None
            s = sorted(self._ring)
        return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]

    @property
    def sample_count(self) -> int:
        with self._mu:
            return len(self._ring)

    def summary(self) -> Dict[str, Any]:
        with self._mu:
            n = self.count
            ring = sorted(self._ring)
            total, mn, mx = self.total, self._min, self._max

        def rank(q: float) -> Optional[float]:
            if not ring:
                return None
            return ring[min(int(q * (len(ring) - 1) + 0.5),
                            len(ring) - 1)]

        return {"count": n, "total": total,
                "mean": (total / n) if n else None,
                "min": mn, "max": mx,
                "p50": rank(0.50), "p95": rank(0.95), "p99": rank(0.99),
                "samples": len(ring)}


class MetricsRegistry:
    """Name → instrument map with get-or-create semantics, plus lazy
    collector sections. One per process (:data:`REGISTRY`); tests may
    build private ones."""

    def __init__(self, hist_samples: int = DEFAULT_HIST_SAMPLES):
        self._mu = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._collectors: Dict[str, Callable[[], Any]] = {}
        self._hist_samples = hist_samples

    # --- instruments --------------------------------------------------
    def counter(self, name: str) -> Counter:
        with self._mu:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._mu:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str,
                  max_samples: Optional[int] = None) -> Histogram:
        with self._mu:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(
                    max_samples or self._hist_samples)
            return h

    # --- absorption of pre-existing stats surfaces --------------------
    def register_collector(self, name: str,
                           fn: Callable[[], Any]) -> None:
        """Merge ``fn()``'s dict under ``name`` at every
        :meth:`snapshot` — the backward-compatible absorption hook:
        ``compile_stats()`` et al. keep their shapes and call sites;
        the registry reports the same numbers without double
        bookkeeping. Re-registering a name replaces the collector
        (module reloads in tests)."""
        with self._mu:
            self._collectors[name] = fn

    def unregister_collector(self, name: str, fn: Callable = None
                             ) -> None:
        """Drop a collector section. With ``fn`` given, only when the
        registered collector equals it (``==``: bound methods compare
        by instance + function, and each attribute access builds a
        fresh bound-method object) — an object tearing itself down
        (ServeController.shutdown) must not remove a successor that
        already replaced it."""
        with self._mu:
            if fn is None or self._collectors.get(name) == fn:
                self._collectors.pop(name, None)

    # --- readout ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Msgpack-safe point-in-time readout: counters, gauges,
        histogram summaries, then each collector's section. A collector
        that raises contributes an ``{"error": ...}`` section instead
        of killing the stats frame."""
        with self._mu:
            counters = {k: v.value for k, v in self._counters.items()}
            gauges = {k: v.value for k, v in self._gauges.items()}
            hists = {k: v.summary() for k, v in self._hists.items()}
            collectors = list(self._collectors.items())
        out: Dict[str, Any] = {"counters": counters, "gauges": gauges,
                               "histograms": hists}
        for name, fn in collectors:
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — typed into the payload
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def numeric_snapshot(self) -> Dict[str, Any]:
        """Bounded NUMERIC-ONLY readout: counters, gauges, and
        per-histogram ``(count, total)`` pairs — no quantile samples,
        no collector sections. This is the reading the telemetry
        history rings (``obs/history.py``): its size is bounded by the
        instrument count alone, never by traffic."""
        with self._mu:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())
        return {"counters": {k: v.value for k, v in counters},
                "gauges": {k: v.value for k, v in gauges},
                "hists": {k: (h.count, h.total) for k, h in hists}}

    def reset(self) -> None:
        """Drop every instrument and collector (tests)."""
        with self._mu:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._collectors.clear()


#: the process-wide registry every subsystem reports into
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY
