"""Per-operator plan profiling — EXPLAIN ANALYZE for the executor.

PR 5/6 gave queries a span profile ("executor 400 ms") but no DAG
decomposition: a trace could not say WHICH plan node ate the time, paid
the recompile, or rode the device cache. This module attributes the
executor's work to individual :class:`~netsdb_tpu.plan.computations.
Computation` nodes — the reference's per-pipeline-stage ``-DPROFILING``
printouts (``PipelineStage.cc:1084-1101``), structured per node and
per query.

Mechanics mirror the query trace exactly:

* the executor installs an :class:`OperatorRecorder` for one
  execution (:func:`recording`); a ``contextvars.ContextVar`` tracks
  the node currently evaluating (:func:`current_op`), so the layers
  below — staging waits, device-cache hits/misses, XLA retrace ticks
  in ``_cached_jit`` — attribute to the right node with zero plumbing
  (:func:`op_add`);
* worker threads (staging) don't inherit the context var: they capture
  the op record on the consumer's thread at stream construction and
  tick counters explicitly (the ``StagedStream`` discipline);
* cost discipline: with no recorder installed, :func:`op_add` is one
  context-var read and an ``is None`` check; ``micro_bench
  --explain-overhead`` pins the recorded-path cost on the staged fold
  stream (< 1% is the budget).

The finished tree (node id = TOPO POSITION — stable across plan
rebuilds, unlike the process-global ``node_id``) lands in three
places: the active query trace's ``operators`` profile section (so
``GET_TRACE`` ships it and a devcache-warm re-run shows the same tree
shape with different cache counters), the bounded per-(job,
node-label) :class:`OperatorLedger` in the metrics registry (the
cross-query cost signal the fusion mapper and the multi-tenant
scheduler consume — ROADMAP items 2/3), and — for an explicit
``EXECUTE(explain=True)`` — the :func:`explain_capture` holder the
serve handler round-trips in the reply.

Stdlib-only, monotonic-clocked (the obs discipline, static-checked).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock


class OpRecord:
    """One plan node's measured execution: inclusive wall time plus
    the counters the instrumented layers tick while it is the current
    op (device-estimate seconds, chunks/blocks, staged bytes/waits,
    devcache hits/misses, XLA retraces). Thread-safe adds — staging
    workers report into the consumer's record."""

    __slots__ = ("op_id", "kind", "label", "atom", "inputs", "wall_s",
                 "rows_in", "rows_out", "fused", "region", "_mu",
                 "_counters")

    def __init__(self, op_id: int, kind: str, label: str, atom: str,
                 inputs: List[int]):
        self.op_id = op_id
        self.kind = kind
        self.label = label
        self.atom = atom
        self.inputs = list(inputs)
        self.wall_s = 0.0
        self.rows_in: Optional[int] = None
        self.rows_out: Optional[int] = None
        self.fused = False
        #: fusion region id (plan/fusion.py) this node compiled into,
        #: None outside any region — the explain tree renders region
        #: membership and boundaries from this
        self.region: Optional[int] = None
        self._mu = threading.Lock()
        self._counters: Dict[str, float] = {}

    def add(self, counter: str, n: float = 1) -> None:
        with self._mu:
            self._counters[counter] = self._counters.get(counter, 0) + n

    def as_dict(self) -> Dict[str, Any]:
        with self._mu:
            counters = dict(self._counters)
        out: Dict[str, Any] = {
            "id": self.op_id, "kind": self.kind, "label": self.label,
            "atom": self.atom, "inputs": list(self.inputs),
            "wall_s": self.wall_s,
            "device_est_s": counters.get("device_est_s", 0.0)
            + counters.get("stage.wait_s", 0.0),
        }
        if self.rows_in is not None:
            out["rows_in"] = self.rows_in
        if self.rows_out is not None:
            out["rows_out"] = self.rows_out
        if self.fused:
            out["fused"] = True
        if self.region is not None:
            out["region"] = self.region
        if counters:
            out["counters"] = counters
        return out


def rows_of(value) -> Optional[int]:
    """Best-effort row/item count of a node value, metadata-only —
    ColumnTables report rows, host lists/tuples/dicts their length
    (for a dict of grouped partials that is the group count), arrays
    their leading dim; opaque values (paged handles mid-stream) report
    None rather than forcing a materialization."""
    num_rows = getattr(value, "num_rows", None)
    if num_rows is not None:
        try:
            return int(num_rows)
        except (TypeError, ValueError):
            return None
    if isinstance(value, (list, tuple, dict)):
        return len(value)
    shape = getattr(value, "shape", None)
    if shape:
        return int(shape[0])
    return None


def bytes_of(value) -> Optional[int]:
    """Metadata-only byte size of array-shaped values (the executor's
    rows/bytes in-out record); None for host-object values (sizing
    them would iterate + pickle the very data the explain path must
    not touch)."""
    cols = getattr(value, "cols", None)
    if cols is not None:
        try:
            return int(sum(int(getattr(v, "nbytes", 0))
                           for v in cols.values()))
        except (TypeError, ValueError):
            return None
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        try:
            return int(nbytes)
        except (TypeError, ValueError):
            return None
    data = getattr(value, "data", None)  # BlockedTensor
    if data is not None:
        return bytes_of(data)
    return None


class OperatorRecorder:
    """Per-execution operator tree: the executor opens one around a
    plan run, enters :meth:`op` per node, and :meth:`finish` emits the
    msgpack-safe tree + feeds the cross-query ledger."""

    def __init__(self, job_name: str, mode: str = "streamed"):
        self.job_name = job_name
        self.mode = mode
        self._mu = threading.Lock()
        self._nodes: Dict[int, OpRecord] = {}
        self._next = 0
        self._t0 = time.perf_counter()

    def reserve(self, count: int) -> int:
        """Allocate a contiguous op-id block for one plan component —
        an auto-split job (``execute_computations`` recursing per
        component) records every component into ONE tree without id
        collisions. Deterministic: split order is deterministic, so a
        re-run reserves identically (the explain-stability
        contract)."""
        with self._mu:
            base = self._next
            self._next += int(count)
            return base

    @staticmethod
    def _label_of(node: Any) -> str:
        """CANONICAL node label: the declared ``label`` when one
        exists, else ``db:set`` for scans/writes — never the default
        ``output_name``, whose embedded process-global node id would
        make two builds of the same DAG produce different trees (the
        explain-stability contract: a cold run and a devcache-warm
        re-run of one plan must be shape-identical)."""
        label = getattr(node, "label", "") or ""
        if label:
            return label
        db = getattr(node, "db", None)
        set_name = getattr(node, "set_name", None)
        if db and set_name:
            return f"{db}:{set_name}"
        return getattr(node, "op_kind", "?").lower()

    def node(self, op_id: int, node: Any,
             inputs: List[int]) -> OpRecord:
        """Get-or-create the record for topo position ``op_id``."""
        with self._mu:
            rec = self._nodes.get(op_id)
            if rec is None:
                rec = self._nodes[op_id] = OpRecord(
                    op_id, getattr(node, "op_kind", "?"),
                    self._label_of(node),
                    node.plan_atom() if hasattr(node, "plan_atom")
                    else "", inputs)
            return rec

    @contextlib.contextmanager
    def op(self, op_id: int, node: Any, inputs: List[int],
           in_vals: Optional[List[Any]] = None) -> Iterator[OpRecord]:
        """Time one node's evaluation inclusively and install it as the
        current op for the dynamic extent — staging/devcache/jit ticks
        attribute here. Nodes evaluate sequentially in the topo loop,
        so the per-node walls SUM to within the executor span (the
        EXPLAIN ANALYZE invariant the tests pin)."""
        rec = self.node(op_id, node, inputs)
        if in_vals:
            rows = [rows_of(v) for v in in_vals]
            known = [r for r in rows if r is not None]
            if known:
                rec.rows_in = int(sum(known))
            nb = [bytes_of(v) for v in in_vals]
            nb_known = [b for b in nb if b is not None]
            if nb_known:
                rec.add("bytes_in", int(sum(nb_known)))
        token = _current_op.set(rec)
        t0 = time.perf_counter()
        try:
            yield rec
        finally:
            rec.wall_s += time.perf_counter() - t0
            _current_op.reset(token)

    def mark_fused(self, topo: List[Any], wall_s: float,
                   device_est_s: float) -> None:
        """Whole-plan jit path: XLA fused every node into ONE program,
        so per-node times do not exist — record the tree SHAPE (the
        plan still explains) with each node marked ``fused`` and a
        synthetic root carrying the program's measured time."""
        base = self.reserve(len(topo) + 1)
        self.mode = "whole_plan_jit" if base == 0 else "mixed"
        pos = {n.node_id: base + i for i, n in enumerate(topo)}
        for n in topo:
            rec = self.node(pos[n.node_id], n,
                            [pos[x.node_id] for x in n.inputs])
            rec.fused = True
        root = self.node(base + len(topo), _FusedRoot(),
                         [pos[n.node_id] for n in topo])
        root.wall_s = wall_s
        root.add("device_est_s", device_est_s)

    def tree(self) -> Dict[str, Any]:
        with self._mu:
            nodes = [self._nodes[k].as_dict()
                     for k in sorted(self._nodes)]
        total = sum(n["wall_s"] for n in nodes)
        return {"job": self.job_name, "mode": self.mode,
                "nodes": nodes, "total_wall_s": total}

    def finish(self) -> Dict[str, Any]:
        """Emit the tree: attach to the active query trace (the
        profile's ``operators`` section), deposit into an active
        :func:`explain_capture`, and aggregate every node into the
        bounded per-(job, label) ledger."""
        # symbol import from the MODULE: the package re-exports a
        # `trace` FUNCTION, so `from netsdb_tpu.obs import trace`
        # would resolve to that instead of the module
        from netsdb_tpu.obs.trace import current_trace

        tree = self.tree()
        tr = current_trace()
        if tr is not None:
            tr.attach_section("operators", tree)
        holder = _capture_var.get()
        if holder is not None:
            holder["operators"] = tree
        for n in tree["nodes"]:
            LEDGER.add(self.job_name, f"{n['kind']}:{n['label']}", n)
        return tree


class _FusedRoot:
    """Synthetic node standing for the one fused XLA program of a
    whole-plan jit execution."""

    op_kind = "WholePlanJit"
    label = "whole_plan_jit"

    def plan_atom(self) -> str:
        return "whole_plan <= JIT(<all nodes fused by XLA>)"


class OperatorLedger:
    """Bounded cross-query aggregate: (job, node-label) → summed
    wall/device/chunk/trace counters + execution count. The registry's
    ``operators`` section — the per-node cost model feed (a mean cost
    per executed operator, queryable without tracing every request).
    Overflow beyond ``max_keys`` lands in one bucket so a label-
    fabricating client cannot grow daemon memory."""

    #: the per-node numeric fields worth aggregating across queries
    FIELDS = ("wall_s", "device_est_s")
    COUNTER_FIELDS = ("chunks", "blocks", "traces", "devcache.hits",
                      "devcache.misses", "stage.wait_s", "stage.bytes",
                      "bytes_in")

    def __init__(self, max_keys: int = 2048):
        self._mu = TrackedLock("OperatorLedger._mu")
        self._max = int(max_keys)
        self._rows: Dict[Tuple[str, str], Dict[str, float]] = {}

    def add(self, job: str, label: str, node: Dict[str, Any]) -> None:
        key = (str(job), str(label))
        with self._mu:
            row = self._rows.get(key)
            if row is None:
                if len(self._rows) >= self._max:
                    key = ("overflow", "*")
                    row = self._rows.setdefault(key, {})
                    _metrics.REGISTRY.counter(
                        "obs.operators_overflow").inc()
                else:
                    row = self._rows[key] = {}
            row["count"] = row.get("count", 0) + 1
            for f in self.FIELDS:
                row[f] = row.get(f, 0.0) + float(node.get(f) or 0.0)
            counters = node.get("counters") or {}
            for f in self.COUNTER_FIELDS:
                v = counters.get(f)
                if v:
                    row[f] = row.get(f, 0.0) + float(v)

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """{job: {label: {field: total}}} — the registry section."""
        with self._mu:
            out: Dict[str, Dict[str, Dict[str, float]]] = {}
            for (job, label), row in self._rows.items():
                out.setdefault(job, {})[label] = dict(row)
            return out

    def job_rows(self, job: str) -> Dict[str, Dict[str, float]]:
        """ONE job's {label: {field: total}} rows — the fusion cost
        model's per-execution read (copying only the queried job's
        rows keeps the contended section O(labels-of-one-job), not
        O(whole ledger), on the serve hot path)."""
        job = str(job)
        with self._mu:
            return {label: dict(row)
                    for (j, label), row in self._rows.items()
                    if j == job}

    def reset(self) -> None:
        with self._mu:
            self._rows.clear()


#: process ledger, exported as the registry's "operators" section
LEDGER = OperatorLedger()
_metrics.REGISTRY.register_collector("operators", LEDGER.snapshot)

_current_op: "contextvars.ContextVar[Optional[OpRecord]]" = \
    contextvars.ContextVar("netsdb_obs_op", default=None)
_current_rec: "contextvars.ContextVar[Optional[OperatorRecorder]]" = \
    contextvars.ContextVar("netsdb_obs_oprec", default=None)
_capture_var: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = \
    contextvars.ContextVar("netsdb_obs_explain", default=None)


def current_op() -> Optional[OpRecord]:
    """The node currently evaluating (None outside a recorded
    execution) — what staging streams capture on the consumer
    thread."""
    return _current_op.get()


def current_recorder() -> Optional[OperatorRecorder]:
    return _current_rec.get()


def op_add(counter: str, n: float = 1) -> None:
    """Tick a counter on the current operator (no-op without one —
    one context-var read on the unrecorded fast path)."""
    rec = _current_op.get()
    if rec is not None:
        rec.add(counter, n)


def should_record(config=None) -> bool:
    """True when this execution wants an operator tree: an explicit
    ``explain=True`` capture is active (always honored), or the query
    is traced AND ``config.obs_explain`` is on."""
    if _capture_var.get() is not None:
        return True
    if config is not None and not getattr(config, "obs_explain", True):
        return False
    from netsdb_tpu.obs.trace import current_trace

    return current_trace() is not None


@contextlib.contextmanager
def recording(job_name: str, config=None,
              force: bool = False) -> Iterator[Optional[OperatorRecorder]]:
    """Install an :class:`OperatorRecorder` for one execution when
    :func:`should_record` says so (or ``force``); finish it on exit.
    Yields None — and records nothing — otherwise, or when a recorder
    is already active (a recursive ``execute_computations`` auto-split
    joins the outer recording rather than shadowing it)."""
    if _current_rec.get() is not None or not (
            force or should_record(config)):
        yield None
        return
    rec = OperatorRecorder(job_name)
    token = _current_rec.set(rec)
    try:
        yield rec
    finally:
        _current_rec.reset(token)
        rec.finish()


@contextlib.contextmanager
def explain_capture() -> Iterator[Dict[str, Any]]:
    """Force-record the next execution in this context and hand its
    tree back: the serve ``EXECUTE(explain=True)`` handler wraps the
    job in this and round-trips ``holder["operators"]`` in the
    reply."""
    holder: Dict[str, Any] = {"operators": None}
    token = _capture_var.set(holder)
    try:
        yield holder
    finally:
        _capture_var.reset(token)


# ---------------------------------------------------------------------
# rendering — the classic EXPLAIN ANALYZE tree (cli `obs --explain`)
# ---------------------------------------------------------------------

def render_tree(tree: Dict[str, Any],
                total_s: Optional[float] = None) -> str:
    """Text rendering of one operator tree, sinks at the root, inputs
    indented below — per node: kind/label, wall ms, % of the plan
    total (or of ``total_s`` when the caller passes the profile's
    total), rows in/out and the interesting counters."""
    nodes = {n["id"]: n for n in tree.get("nodes") or []}
    if not nodes:
        return "(no operator profile)"
    consumed = set()
    for n in nodes.values():
        consumed.update(n.get("inputs") or ())
    roots = [i for i in sorted(nodes) if i not in consumed]
    denom = total_s if total_s else (tree.get("total_wall_s") or 0.0)
    lines = [f"EXPLAIN ANALYZE  job={tree.get('job')} "
             f"mode={tree.get('mode')} "
             f"total={1e3 * (tree.get('total_wall_s') or 0.0):.2f}ms"]

    def fmt(n: Dict[str, Any]) -> str:
        wall = n.get("wall_s") or 0.0
        pct = (100.0 * wall / denom) if denom else 0.0
        bits = [f"{n.get('kind')}[{n.get('label')}]",
                f"wall={1e3 * wall:.2f}ms ({pct:.1f}%)"]
        dev = n.get("device_est_s") or 0.0
        if dev:
            bits.append(f"device≈{1e3 * dev:.2f}ms")
        if n.get("rows_in") is not None:
            bits.append(f"rows_in={n['rows_in']}")
        if n.get("rows_out") is not None:
            bits.append(f"rows_out={n['rows_out']}")
        if n.get("region") is not None:
            # fusion region membership (plan/fusion.py): every node of
            # region rN compiled into ONE XLA program
            bits.append(f"region=r{n['region']}"
                        + ("" if n.get("fused") else "*"))
        elif n.get("fused"):
            bits.append("fused")
        c = n.get("counters") or {}
        keep = {k: v for k, v in c.items()
                if k in ("chunks", "blocks", "pairs", "traces",
                         "region_nodes", "devcache.hits",
                         "devcache.misses", "stage.chunks",
                         "stage.bytes")}
        if keep:
            bits.append(" ".join(f"{k}={int(v)}" for k, v in
                                 sorted(keep.items())))
        return "  ".join(bits)

    def walk(op_id: int, depth: int, seen: set,
             parent_region=None) -> None:
        n = nodes.get(op_id)
        if n is None:
            return
        marker = "-> " if depth else ""
        region = n.get("region")
        if depth and region != parent_region:
            # fusion-region boundary: the edge crosses out of (or
            # into) a fused program — the materialization point
            marker = "=> " if region is None else f"┆r{region} "
        lines.append(f"{'  ' * depth}{marker}{fmt(n)}")
        if op_id in seen:  # shared subgraph: print once per parent,
            return         # recurse once
        seen.add(op_id)
        for i in n.get("inputs") or ():
            walk(i, depth + 1, seen, region)

    seen: set = set()
    for r in roots:
        walk(r, 0, seen)
    return "\n".join(lines)


def render_shard_forest(shard_ops: Optional[Dict[str, Any]],
                        total_s: Optional[float] = None) -> str:
    """Text rendering of a scatter-gather query's per-shard EXPLAIN
    forest (``shard_operators``): each member's subplan tree rendered
    by the SAME :func:`render_tree` the coordinator tree gets — so
    region ids, ``┆rN`` boundary markers and the ``*`` streaming-
    anchor annotation are shape-identical across the distributed tree.
    Members sort by address for deterministic output under one qid."""
    if not shard_ops:
        return "(no shard operator forest)"
    parts = []
    for addr in sorted(shard_ops):
        tree = shard_ops[addr] or {}
        parts.append(f"-- shard {addr}")
        parts.append(render_tree(tree, total_s))
    return "\n".join(parts)
