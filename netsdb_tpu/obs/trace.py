"""Query-scoped tracing — the ``-DPROFILING`` spans, structured.

The reference answers "where did this query spend its time" with
wall-clock spans around planning and every pipeline phase
(``QuerySchedulerServer.cc:1336-1341``, ``PipelineStage.cc:1084-1101``)
printed per stage. Here the same spans are STRUCTURED and query-scoped:
a :class:`QueryTrace` — keyed by a query id minted client-side and
carried in frame metadata (``serve/protocol.QUERY_ID_KEY``) — collects
nested spans across client send → daemon dispatch → planner → executor
chunk loops → staging upload waits → device-cache hits, each with a
monotonic start offset, duration, category and counters (bytes staged,
chunks, traces triggered, cache hits).

Propagation is a ``contextvars.ContextVar``: the serve handler (or the
client's request path) installs the trace, and every instrumented layer
below reads it back with :func:`current_trace` — zero plumbing through
call signatures. Worker threads (staging) don't inherit the context;
they capture the trace at stream construction on the consumer's thread
and add COUNTERS only (cross-thread span nesting would lie about
concurrency).

Cost discipline: tracing is ALWAYS ON (``config.obs_enabled`` is the
kill switch). The no-trace fast path of :func:`span` is one context-var
read and one ``is None`` check; with a trace active, a span is two
``perf_counter`` reads and one list append under a lock.
``micro_bench --obs-overhead`` pins the end-to-end cost on the staged
fold stream (< 3% is the budget).

Completed traces land in a bounded :class:`TraceRing` — the daemon
keeps the last N query profiles for the ``GET_TRACE`` frame; client
processes keep their own ring (:data:`DEFAULT_RING`) for local
introspection. All clocks are ``time.perf_counter`` — monotonic, never
wall (the serve clock discipline, enforced by the static checks).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

from netsdb_tpu.obs import metrics as _metrics

#: process-wide kill switch (config.obs_enabled mirrors into this via
#: set_enabled at daemon/CLI startup); when off, no trace is ever
#: installed so every span call takes the one-check fast path
_enabled = True


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def new_query_id() -> str:
    """Client-side query-id mint — one per logical query, carried in
    frame metadata so the daemon's spans join the client's."""
    return uuid.uuid4().hex[:16]


class Span:
    """One timed region inside a trace. ``start_s`` is the offset from
    the trace's own start (monotonic deltas — profile timelines line up
    without any cross-process clock agreement)."""

    __slots__ = ("name", "category", "start_s", "duration_s", "depth",
                 "counters")

    def __init__(self, name: str, category: str, start_s: float,
                 depth: int):
        self.name = name
        self.category = category
        self.start_s = start_s
        self.duration_s = 0.0
        self.depth = depth
        self.counters: Dict[str, float] = {}

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "category": self.category,
                             "start_s": self.start_s,
                             "duration_s": self.duration_s,
                             "depth": self.depth}
        if self.counters:
            d["counters"] = dict(self.counters)
        return d


class QueryTrace:
    """All spans + counters of one logical query on one side of the
    wire. ``origin`` says which side ("client"/"server"/"local").
    Thread-safe for counter adds and span records (staging threads
    report into the consumer's trace); span DEPTH tracks per-thread
    nesting so concurrent reporters can't corrupt each other's
    stacks."""

    def __init__(self, qid: str, origin: str = "local",
                 ring: Optional["TraceRing"] = None):
        self.qid = qid
        self.origin = origin
        self._ring = ring
        self._t0 = time.perf_counter()
        self._mu = threading.Lock()
        self._spans: List[Span] = []
        self._counters: Dict[str, float] = {}
        self._depth = threading.local()
        self.total_s: Optional[float] = None  # set by finish()

    # --- spans --------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, category: str = "") -> Iterator[Span]:
        depth = getattr(self._depth, "v", 0)
        self._depth.v = depth + 1
        sp = Span(name, category, time.perf_counter() - self._t0, depth)
        try:
            yield sp
        finally:
            sp.duration_s = (time.perf_counter() - self._t0) - sp.start_s
            self._depth.v = depth
            with self._mu:
                self._spans.append(sp)

    def record(self, name: str, duration_s: float, category: str = "",
               start_s: Optional[float] = None, **counters) -> None:
        """Record an already-measured region (e.g. the frame decode
        that finished before the trace could open)."""
        if start_s is None:
            start_s = (time.perf_counter() - self._t0) - duration_s
        sp = Span(name, category, start_s, getattr(self._depth, "v", 0))
        sp.duration_s = duration_s
        if counters:
            sp.counters.update(counters)
        with self._mu:
            self._spans.append(sp)

    def backdate(self, seconds: float) -> None:
        """Shift the trace start ``seconds`` earlier — for work that
        finished before the trace could open (the serve frame decode):
        a span then :meth:`record`-ed at offset 0 occupies real
        timeline ahead of the first live span instead of overlapping
        it, and ``total_s`` covers it."""
        self._t0 -= float(seconds)

    # --- counters -----------------------------------------------------
    def add(self, counter: str, n: float = 1) -> None:
        with self._mu:
            self._counters[counter] = self._counters.get(counter, 0) + n

    # --- lifecycle ----------------------------------------------------
    def finish(self) -> Dict[str, Any]:
        """Close the trace (idempotent on total_s) and push its profile
        to the ring. Returns the profile."""
        if self.total_s is None:
            self.total_s = time.perf_counter() - self._t0
        prof = self.profile()
        if self._ring is not None:
            self._ring.push(prof)
        return prof

    def profile(self) -> Dict[str, Any]:
        """Msgpack-safe profile dict — what GET_TRACE ships."""
        with self._mu:
            spans = [s.as_dict() for s in
                     sorted(self._spans, key=lambda s: s.start_s)]
            counters = dict(self._counters)
        return {"qid": self.qid, "origin": self.origin,
                "total_s": self.total_s, "spans": spans,
                "counters": counters}


class TraceRing:
    """Bounded ring of completed query profiles — the GET_TRACE
    source. Push-side cheap; ``last(n)`` returns newest-last."""

    def __init__(self, capacity: int = 64):
        self._mu = threading.Lock()
        self._cap = max(int(capacity), 1)
        self._items: List[Dict[str, Any]] = []

    def push(self, profile: Dict[str, Any]) -> None:
        with self._mu:
            self._items.append(profile)
            if len(self._items) > self._cap:
                del self._items[:len(self._items) - self._cap]

    def last(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._mu:
            items = list(self._items)
        return items if n is None else items[-int(n):]

    def find(self, qid: str) -> List[Dict[str, Any]]:
        with self._mu:
            return [p for p in self._items if p.get("qid") == qid]

    def clear(self) -> None:
        with self._mu:
            self._items.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)


#: ring for traces opened without an explicit ring (client-side
#: requests, in-process queries) — daemons own a per-controller ring
DEFAULT_RING = TraceRing()

_current: "contextvars.ContextVar[Optional[QueryTrace]]" = \
    contextvars.ContextVar("netsdb_obs_trace", default=None)


def current_trace() -> Optional[QueryTrace]:
    return _current.get()


@contextlib.contextmanager
def trace(qid: Optional[str] = None, origin: str = "local",
          ring: Optional[TraceRing] = None) -> Iterator[Optional[QueryTrace]]:
    """Install a :class:`QueryTrace` as the current context's trace for
    the duration; finish (and ring-push) it on exit. Yields None — and
    installs nothing — when tracing is disabled or a trace is already
    active (a nested logical query joins the outer trace's spans
    instead of shadowing it)."""
    if not _enabled or _current.get() is not None:
        yield None
        return
    tr = QueryTrace(qid or new_query_id(), origin,
                    ring if ring is not None else DEFAULT_RING)
    token = _current.set(tr)
    try:
        yield tr
    finally:
        _current.reset(token)
        tr.finish()
        _metrics.REGISTRY.counter(f"obs.traces.{origin}").inc()


@contextlib.contextmanager
def span(name: str, category: str = "") -> Iterator[Optional[Span]]:
    """Span on the current trace, or a no-op when none is active — the
    form every instrumented layer uses (executor loops, staging waits,
    serve dispatch). The inactive path is one context-var read."""
    tr = _current.get()
    if tr is None:
        yield None
        return
    with tr.span(name, category) as sp:
        yield sp


def add(counter: str, n: float = 1) -> None:
    """Bump a counter on the current trace (no-op without one)."""
    tr = _current.get()
    if tr is not None:
        tr.add(counter, n)
