"""Query-scoped tracing — the ``-DPROFILING`` spans, structured.

The reference answers "where did this query spend its time" with
wall-clock spans around planning and every pipeline phase
(``QuerySchedulerServer.cc:1336-1341``, ``PipelineStage.cc:1084-1101``)
printed per stage. Here the same spans are STRUCTURED and query-scoped:
a :class:`QueryTrace` — keyed by a query id minted client-side and
carried in frame metadata (``serve/protocol.QUERY_ID_KEY``) — collects
nested spans across client send → daemon dispatch → planner → executor
chunk loops → staging upload waits → device-cache hits, each with a
monotonic start offset, duration, category and counters (bytes staged,
chunks, traces triggered, cache hits).

Propagation is a ``contextvars.ContextVar``: the serve handler (or the
client's request path) installs the trace, and every instrumented layer
below reads it back with :func:`current_trace` — zero plumbing through
call signatures. Worker threads (staging) don't inherit the context;
they capture the trace at stream construction on the consumer's thread
and add COUNTERS only (cross-thread span nesting would lie about
concurrency).

Cost discipline: tracing is ALWAYS ON (``config.obs_enabled`` is the
kill switch). The no-trace fast path of :func:`span` is one context-var
read and one ``is None`` check; with a trace active, a span is two
``perf_counter`` reads and one list append under a lock.
``micro_bench --obs-overhead`` pins the end-to-end cost on the staged
fold stream (< 3% is the budget).

Completed traces land in a bounded :class:`TraceRing` — the daemon
keeps the last N query profiles for the ``GET_TRACE`` frame; client
processes keep their own ring (:data:`DEFAULT_RING`) for local
introspection. All clocks are ``time.perf_counter`` — monotonic, never
wall (the serve clock discipline, enforced by the static checks).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock

#: process-wide kill switch (config.obs_enabled mirrors into this via
#: set_enabled at daemon/CLI startup); when off, no trace is ever
#: installed so every span call takes the one-check fast path
_enabled = True


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def new_query_id() -> str:
    """Client-side query-id mint — one per logical query, carried in
    frame metadata so the daemon's spans join the client's.

    HOT-PATH callers must not call this directly: qid minting decides
    whether a whole query gets traced end-to-end (client spans shipped
    via PUT_TRACE, a server profile in the ring, optional device
    profiling), and at high QPS that cost must be SAMPLED, not paid per
    request. Mint through :func:`sample_qid` (``config.
    obs_trace_sample``) — the static check in
    ``tests/test_static_checks.py`` bans ``new_query_id`` outside
    ``obs/``."""
    return uuid.uuid4().hex[:16]


class QidSampler:
    """Deterministic 1-in-N qid mint with its OWN round-robin phase.

    One per caller (each ``RemoteClient`` owns one): a PROCESS-wide
    counter phase-locks under interleaved callers — two clients
    alternating at sample=4 would give one of them ``n % 4 == 0``
    never (starved of tracing forever) and the other 1-in-2. Per-caller
    phase keeps ``RemoteClient(trace_sample=N)`` meaning exactly
    1-in-N of THAT client's requests."""

    def __init__(self):
        self._mu = threading.Lock()
        self._n = 0

    def sample(self, sample: int = 1) -> Optional[str]:
        """A fresh query id for 1 in every ``sample`` calls
        (deterministic round-robin, not random — tests and capacity
        planning both want an exact rate), None otherwise.
        ``sample <= 1`` traces everything (the PR 5 behavior); the
        serve client threads ``config.obs_trace_sample`` through here
        so high-QPS traffic traces at 1/N cost. Tracing disabled ⇒
        always None."""
        if not _enabled:
            return None
        if sample <= 1:
            return new_query_id()
        with self._mu:
            self._n += 1
            hit = self._n % int(sample) == 0
        if not hit:
            _metrics.REGISTRY.counter("obs.qid_sampled_out").inc()
            return None
        return new_query_id()


# process-default sampler for callers without their own (module-level
# sample_qid); clients mint through their own QidSampler
_default_sampler = QidSampler()


def sample_qid(sample: int = 1) -> Optional[str]:
    """Module-level convenience over the process-default
    :class:`QidSampler` — see its docstring; per-client callers hold
    their own sampler so interleaving can't skew their rate."""
    return _default_sampler.sample(sample)


class Span:
    """One timed region inside a trace. ``start_s`` is the offset from
    the trace's own start (monotonic deltas — profile timelines line up
    without any cross-process clock agreement)."""

    __slots__ = ("name", "category", "start_s", "duration_s", "depth",
                 "counters")

    def __init__(self, name: str, category: str, start_s: float,
                 depth: int):
        self.name = name
        self.category = category
        self.start_s = start_s
        self.duration_s = 0.0
        self.depth = depth
        self.counters: Dict[str, float] = {}

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "category": self.category,
                             "start_s": self.start_s,
                             "duration_s": self.duration_s,
                             "depth": self.depth}
        if self.counters:
            d["counters"] = dict(self.counters)
        return d


class QueryTrace:
    """All spans + counters of one logical query on one side of the
    wire. ``origin`` says which side ("client"/"server"/"local").
    Thread-safe for counter adds and span records (staging threads
    report into the consumer's trace); span DEPTH tracks per-thread
    nesting so concurrent reporters can't corrupt each other's
    stacks."""

    def __init__(self, qid: str, origin: str = "local",
                 ring: Optional["TraceRing"] = None):
        self.qid = qid
        self.origin = origin
        self._ring = ring
        self._t0 = time.perf_counter()
        self._mu = threading.Lock()
        self._spans: List[Span] = []
        self._counters: Dict[str, float] = {}
        self._meta: Dict[str, Any] = {}
        self._sections: Dict[str, Any] = {}
        self._depth = threading.local()
        self.total_s: Optional[float] = None  # set by finish()

    # --- spans --------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, category: str = "") -> Iterator[Span]:
        depth = getattr(self._depth, "v", 0)
        self._depth.v = depth + 1
        sp = Span(name, category, time.perf_counter() - self._t0, depth)
        try:
            yield sp
        finally:
            sp.duration_s = (time.perf_counter() - self._t0) - sp.start_s
            self._depth.v = depth
            with self._mu:
                self._spans.append(sp)

    def record(self, name: str, duration_s: float, category: str = "",
               start_s: Optional[float] = None, **counters) -> None:
        """Record an already-measured region (e.g. the frame decode
        that finished before the trace could open)."""
        if start_s is None:
            start_s = (time.perf_counter() - self._t0) - duration_s
        sp = Span(name, category, start_s, getattr(self._depth, "v", 0))
        sp.duration_s = duration_s
        if counters:
            sp.counters.update(counters)
        with self._mu:
            self._spans.append(sp)

    def backdate(self, seconds: float) -> None:
        """Shift the trace start ``seconds`` earlier — for work that
        finished before the trace could open (the serve frame decode):
        a span then :meth:`record`-ed at offset 0 occupies real
        timeline ahead of the first live span instead of overlapping
        it, and ``total_s`` covers it."""
        self._t0 -= float(seconds)

    # --- counters -----------------------------------------------------
    def add(self, counter: str, n: float = 1) -> None:
        with self._mu:
            self._counters[counter] = self._counters.get(counter, 0) + n

    def annotate(self, key: str, value: Any) -> None:
        """Attach a non-numeric fact to the profile (``meta`` section):
        the device-profile directory, the client identity, a sampling
        note — things counters cannot carry."""
        with self._mu:
            self._meta[str(key)] = value

    def attach_section(self, name: str, payload: Any) -> None:
        """Attach a structured top-level profile section BEFORE the
        trace finishes — the in-process form of
        :meth:`TraceRing.merge_section` (which handles sections that
        arrive after the push, e.g. PUT_TRACE). The executor's
        per-operator tree rides here as ``operators``."""
        with self._mu:
            self._sections[str(name)] = payload

    # --- lifecycle ----------------------------------------------------
    def finish(self) -> Dict[str, Any]:
        """Close the trace (idempotent on total_s) and push its profile
        to the ring. Returns the profile."""
        if self.total_s is None:
            self.total_s = time.perf_counter() - self._t0
        prof = self.profile()
        if self._ring is not None:
            self._ring.push(prof)
        return prof

    def profile(self) -> Dict[str, Any]:
        """Msgpack-safe profile dict — what GET_TRACE ships.

        ``host_device`` splits the query's total into an estimated
        device share and the host remainder. The device share sums the
        counters the instrumented layers already measure —
        ``device.est_s`` (time inside dispatched jitted steps, the
        ``scan_slope``-style wall timing around each fold/tensor step)
        plus ``stage.wait_s`` (time the consumer blocked on a staged
        host→device upload). It is an ESTIMATE (dispatch-inclusive;
        exact device timelines come from the opt-in per-qid
        ``jax.profiler`` session whose directory rides ``meta``)."""
        with self._mu:
            spans = [s.as_dict() for s in
                     sorted(self._spans, key=lambda s: s.start_s)]
            counters = dict(self._counters)
            meta = dict(self._meta)
            sections = dict(self._sections)
        out: Dict[str, Any] = {"qid": self.qid, "origin": self.origin,
                               "total_s": self.total_s, "spans": spans,
                               "counters": counters}
        out.update(sections)
        if meta:
            out["meta"] = meta
        if self.total_s is not None:
            dev = (counters.get("device.est_s", 0.0)
                   + counters.get("stage.wait_s", 0.0))
            dev = min(dev, self.total_s)
            out["host_device"] = {
                "device_est_s": dev,
                "host_s": max(self.total_s - dev, 0.0)}
        return out


class TraceRing:
    """Bounded ring of completed query profiles — the GET_TRACE
    source. Push-side cheap; ``last(n)`` returns newest-last."""

    def __init__(self, capacity: int = 64, pending_capacity: int = 32):
        self._mu = TrackedLock("TraceRing._mu")
        self._cap = max(int(capacity), 1)
        self._items: List[Dict[str, Any]] = []
        # sections that arrived BEFORE their profile ringed (the
        # reply-before-ring race, merge_section docstring); qid →
        # {section: payload}, oldest evicted first
        self._pending_cap = max(int(pending_capacity), 1)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def push(self, profile: Dict[str, Any]) -> None:
        with self._mu:
            qid = profile.get("qid")
            pend = self._pending.pop(qid, None) if qid else None
            if pend:
                profile = {**profile, **pend}
            self._items.append(profile)
            if len(self._items) > self._cap:
                del self._items[:len(self._items) - self._cap]

    def last(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._mu:
            items = list(self._items)
        return items if n is None else items[-int(n):]

    def find(self, qid: str) -> List[Dict[str, Any]]:
        with self._mu:
            return [p for p in self._items if p.get("qid") == qid]

    def merge_section(self, qid: str, section: str, payload: Any) -> bool:
        """Attach ``payload`` under ``section`` on every ringed profile
        of ``qid`` — the PUT_TRACE merge: a client's shipped span
        profile joins the daemon profile minted under the same query
        id, so GET_TRACE returns ONE end-to-end decomposition. Returns
        True when at least one ringed profile matched.

        NO causal ordering protects this: the reply goes out INSIDE
        the trace context (``_dispatch_traced``), the ring push happens
        at trace finish AFTER it — so a fast client shipping on its
        own connection can beat the push. An unmatched section is
        therefore BUFFERED (bounded, oldest-evicted) and
        :meth:`push` folds it into the profile when it lands; only a
        qid that never rings (rotated out, never sampled) stays
        unmatched.

        COPY-ON-MERGE: ``last``/``find`` hand out the ringed dicts
        themselves (a GET_TRACE reply may be mid-serialization on
        another connection) — mutating one in place would change a
        dict under iteration. The merge REPLACES the ring slot with an
        extended shallow copy instead; readers holding the old dict
        keep a consistent (pre-merge) profile."""
        with self._mu:
            hit = False
            for i, p in enumerate(self._items):
                if p.get("qid") == qid:
                    merged = dict(p)
                    merged[section] = payload
                    self._items[i] = merged
                    hit = True
            if not hit:
                self._pending.setdefault(qid, {})[section] = payload
                while len(self._pending) > self._pending_cap:
                    self._pending.pop(next(iter(self._pending)))
            return hit

    def clear(self) -> None:
        with self._mu:
            self._items.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)


#: ring for traces opened without an explicit ring (client-side
#: requests, in-process queries) — daemons own a per-controller ring
DEFAULT_RING = TraceRing()

_current: "contextvars.ContextVar[Optional[QueryTrace]]" = \
    contextvars.ContextVar("netsdb_obs_trace", default=None)


def current_trace() -> Optional[QueryTrace]:
    return _current.get()


@contextlib.contextmanager
def trace(qid: Optional[str] = None, origin: str = "local",
          ring: Optional[TraceRing] = None) -> Iterator[Optional[QueryTrace]]:
    """Install a :class:`QueryTrace` as the current context's trace for
    the duration; finish (and ring-push) it on exit. Yields None — and
    installs nothing — when tracing is disabled or a trace is already
    active (a nested logical query joins the outer trace's spans
    instead of shadowing it)."""
    if not _enabled or _current.get() is not None:
        yield None
        return
    tr = QueryTrace(qid or new_query_id(), origin,
                    ring if ring is not None else DEFAULT_RING)
    token = _current.set(tr)
    try:
        yield tr
    finally:
        _current.reset(token)
        tr.finish()
        _metrics.REGISTRY.counter(f"obs.traces.{origin}").inc()


@contextlib.contextmanager
def span(name: str, category: str = "") -> Iterator[Optional[Span]]:
    """Span on the current trace, or a no-op when none is active — the
    form every instrumented layer uses (executor loops, staging waits,
    serve dispatch). The inactive path is one context-var read."""
    tr = _current.get()
    if tr is None:
        yield None
        return
    with tr.span(name, category) as sp:
        yield sp


def add(counter: str, n: float = 1) -> None:
    """Bump a counter on the current trace (no-op without one)."""
    tr = _current.get()
    if tr is not None:
        tr.add(counter, n)
