"""Structured slow-query log — a bounded on-disk profile ring.

The trace ring (``obs/trace.TraceRing``) is memory-only and FIFO: one
burst of fast queries evicts the slow outlier an operator most wants
to see, and a daemon restart loses everything. This module persists
exactly the outliers: any query whose trace total exceeds
``config.obs_slow_query_s`` gets its FULL profile (spans, counters,
host/device split, meta) written as one JSON file under
``<root>/slowlog/``, pruned to the newest ``config.obs_slowlog_entries``
files — a year of serving holds a bounded directory, and the entries
survive restarts (sequence numbers continue from what is on disk).

File name: ``slow-<seq 12 digits>-<qid>.json`` — lexicographic order
IS age order, so pruning and newest-last listing are directory scans,
no index file to corrupt. Writes are atomic (tmp + rename): a crash
mid-record leaves either the old directory or the new file, never a
torn JSON.

Inspection: the serve ``GET_TRACE`` frame with ``{"slow": true}``
returns the persisted entries (``netsdb_tpu obs --slowlog``); the
``HEALTH`` frame carries the summary counts.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock

_PREFIX = "slow-"
_SUFFIX = ".json"


class SlowQueryLog:
    """Bounded on-disk ring of slow-query profiles."""

    def __init__(self, root_dir: str, capacity: int = 64,
                 threshold_s: Optional[float] = None):
        self.dir = os.path.join(root_dir, "slowlog")
        self.capacity = max(int(capacity), 1)
        self.threshold_s = threshold_s
        self._mu = TrackedLock("SlowQueryLog._mu")
        os.makedirs(self.dir, exist_ok=True)
        # restart continuity: the next sequence number follows the
        # newest file already on disk
        self._seq = 0
        for name in self._names():
            try:
                self._seq = max(self._seq,
                                int(name[len(_PREFIX):].split("-", 1)[0]))
            except (ValueError, IndexError):
                continue

    def _names(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.dir)
                          if n.startswith(_PREFIX) and n.endswith(_SUFFIX))
        except OSError:
            return []

    # --- record -------------------------------------------------------
    def record(self, profile: Dict[str, Any]) -> Optional[str]:
        """Persist one profile; returns the file path (None on any
        persistence trouble — losing a slowlog entry must never fail
        the query that produced it)."""
        qid = str(profile.get("qid") or "unknown")[:32]
        with self._mu:
            self._seq += 1
            name = f"{_PREFIX}{self._seq:012d}-{qid}{_SUFFIX}"
            path = os.path.join(self.dir, name)
            tmp = path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(profile, f, default=str)
                os.replace(tmp, path)  # atomic: never a torn JSON
            except (OSError, TypeError, ValueError):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return None
            # prune oldest beyond capacity (lexicographic == age)
            names = self._names()
            for old in names[:max(len(names) - self.capacity, 0)]:
                try:
                    os.remove(os.path.join(self.dir, old))
                except OSError:
                    pass
        _metrics.REGISTRY.counter("obs.slow_queries").inc()
        return path

    def maybe_record(self, profile: Dict[str, Any]) -> Optional[str]:
        """Record iff the profile's total exceeds the threshold
        (None/0 threshold = disabled)."""
        if not self.threshold_s:
            return None
        total = profile.get("total_s")
        if total is None or total < self.threshold_s:
            return None
        return self.record(profile)

    def merge_section(self, qid: str, section: str,
                      payload: Any) -> bool:
        """Attach ``payload`` under ``section`` on every persisted
        entry of ``qid`` — the slowlog half of the PUT_TRACE merge:
        the server persists a slow profile when its trace closes,
        BEFORE the client's spans can possibly arrive (the client only
        ships after the reply), so without this rewrite every slowlog
        entry would permanently lack its ``client`` section. Atomic
        (tmp + rename) like :meth:`record`; returns True when at least
        one entry matched. Bounded work: the directory holds at most
        ``capacity`` files and a qid names at most a handful."""
        qid = str(qid)[:32]
        hit = False
        with self._mu:
            for name in self._names():
                stem = name[len(_PREFIX):-len(_SUFFIX)]
                if stem.split("-", 1)[-1] != qid:
                    continue
                path = os.path.join(self.dir, name)
                tmp = path + ".tmp"
                try:
                    with open(path) as f:
                        prof = json.load(f)
                    prof[section] = payload
                    with open(tmp, "w") as f:
                        json.dump(prof, f, default=str)
                    os.replace(tmp, path)
                    hit = True
                except (OSError, TypeError, ValueError):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        return hit

    # --- inspect ------------------------------------------------------
    def entries(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Persisted profiles, newest LAST (the TraceRing convention).
        Unreadable files are skipped, never fatal."""
        names = self._names()
        if last is not None:
            names = names[-int(last):]
        out = []
        for name in names:
            try:
                with open(os.path.join(self.dir, name)) as f:
                    prof = json.load(f)
            except (OSError, ValueError):
                continue
            prof["slowlog_file"] = name
            out.append(prof)
        return out

    def summary(self) -> Dict[str, Any]:
        names = self._names()
        return {"entries": len(names), "dir": self.dir,
                "threshold_s": self.threshold_s,
                "newest": names[-1] if names else None}
