"""Per-(client, set) resource attribution — who used what.

netsDB meters nothing per tenant; every netsdb_tpu counter so far is
process-global. The multi-tenant scheduler (ROADMAP item 2) cannot
admit, throttle, or bill without knowing which CLIENT consumed which
resources on which SET — this module is that ledger.

Identity rides the wire: :class:`~netsdb_tpu.serve.client.RemoteClient`
attaches its ``client_id`` to every frame
(``serve/protocol.CLIENT_ID_KEY``); the daemon pops it before dispatch
and installs it in a ``contextvars.ContextVar`` for the handler's
dynamic extent (:func:`client_context`) — the same zero-plumbing
propagation the query trace uses. Instrumented layers then call
:func:`account` with a metric and a set scope (``"db:set"``); the
ledger aggregates ``(client, scope) → {metric: total}``.

Accounted today: ``requests`` (per dispatched frame), ``staged_bytes``
/ ``staged_chunks`` (the staging pipeline's uploads), ``devcache.hits``
/ ``devcache.installs`` (whose queries paid the transfers vs rode
them), ``executor.chunks`` (fold-loop work). Anonymous traffic (no
client id on the frame) is aggregated under ``"anon"`` so totals stay
complete.

Worker threads (staging installs) don't inherit the context var —
capture :func:`current_client` on the consumer thread at construction
and pass it explicitly (the trace-capture discipline,
``plan/staging.StagedStream``).

The ledger is a registry COLLECTOR (section ``"attribution"`` of every
``MetricsRegistry.snapshot()``), so the serve ``COLLECT_STATS`` frame
ships it with zero extra plumbing and a leader merges follower
sections like any other stats read. Bounded: at most
:data:`MAX_KEYS` (client, scope) pairs — a client fabricating scopes
cannot grow daemon memory without bound (overflow lands in the
``"overflow"`` bucket and ticks ``attrib.overflow``).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Any, Dict, Iterator, Optional

from netsdb_tpu.obs import metrics as _metrics
from netsdb_tpu.utils.locks import TrackedLock

#: identity for frames that carried no client id — attribution must
#: stay COMPLETE (sum over clients == global counters), so anonymous
#: traffic gets a bucket instead of being dropped
ANON = "anon"

#: bound on distinct (client, scope) pairs the ledger will hold
MAX_KEYS = 4096

_client_var: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("netsdb_obs_client", default=None)


def current_client() -> Optional[str]:
    """The client identity of the current dynamic extent (None outside
    a serve dispatch that carried one)."""
    return _client_var.get()


@contextlib.contextmanager
def client_context(client_id: Optional[str]) -> Iterator[None]:
    """Install ``client_id`` for the duration — the serve dispatch
    wraps each handler in this (None installs nothing: nested/mirrored
    execution keeps the outer identity)."""
    if client_id is None:
        yield
        return
    token = _client_var.set(str(client_id))
    try:
        yield
    finally:
        _client_var.reset(token)


class ResourceLedger:
    """(client, scope) → {metric: total}. Thread-safe, bounded,
    snapshot-table msgpack-safe."""

    def __init__(self, max_keys: int = MAX_KEYS):
        self._mu = TrackedLock("ResourceLedger._mu")
        self._max = int(max_keys)
        self._counts: Dict[Any, Dict[str, float]] = {}

    def add(self, metric: str, n: float = 1, scope: Optional[str] = None,
            client: Optional[str] = None) -> None:
        """One accounting tick. ``client=None`` reads the context var
        (worker threads pass the captured identity explicitly)."""
        if client is None:
            client = _client_var.get() or ANON
        key = (str(client), str(scope) if scope else "*")
        with self._mu:
            d = self._counts.get(key)
            if d is None:
                if len(self._counts) >= self._max:
                    key = ("overflow", "*")
                    d = self._counts.setdefault(key, {})
                    _metrics.REGISTRY.counter("attrib.overflow").inc()
                else:
                    d = self._counts[key] = {}
            d[metric] = d.get(metric, 0) + n

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """{client: {scope: {metric: total}}} — the COLLECT_STATS
        ``attribution`` section."""
        with self._mu:
            out: Dict[str, Dict[str, Dict[str, float]]] = {}
            for (client, scope), metrics in self._counts.items():
                out.setdefault(client, {})[scope] = dict(metrics)
            return out

    def totals(self, client: str) -> Dict[str, float]:
        """One client's metrics summed across scopes (scheduler-quota
        convenience)."""
        with self._mu:
            out: Dict[str, float] = {}
            for (c, _scope), metrics in self._counts.items():
                if c != client:
                    continue
                for m, v in metrics.items():
                    out[m] = out.get(m, 0) + v
            return out

    def reset(self) -> None:
        with self._mu:
            self._counts.clear()


#: the process ledger every instrumented layer reports into; exported
#: as the registry's "attribution" section
LEDGER = ResourceLedger()
_metrics.REGISTRY.register_collector("attribution", LEDGER.snapshot)


def account(metric: str, n: float = 1, scope: Optional[str] = None,
            client: Optional[str] = None) -> None:
    """Tick the process ledger (module-level convenience — the form
    the staging/devcache/executor call sites use)."""
    LEDGER.add(metric, n, scope=scope, client=client)
