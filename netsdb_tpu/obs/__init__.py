"""Unified observability: query-scoped tracing + the central metrics
registry (see ``obs/trace.py`` and ``obs/metrics.py``). The public
surface other layers import::

    from netsdb_tpu import obs

    with obs.span("executor.fold_stream", "executor") as sp: ...
    obs.add("devcache.hits")
    obs.REGISTRY.counter("serve.client.retries").inc()

Spans/counters are no-ops unless a query trace is installed
(``obs.trace(...)`` — the serve dispatch and the wire client do this);
registry instruments are always live. Stdlib-only by design: the
JAX-free wire client imports this module.
"""

from netsdb_tpu.obs import attrib  # noqa: F401 — registers "attribution"
from netsdb_tpu.obs import operators  # noqa: F401 — registers "operators"
from netsdb_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    registry,
)
from netsdb_tpu.obs.trace import (  # noqa: F401
    DEFAULT_RING,
    QidSampler,
    QueryTrace,
    Span,
    TraceRing,
    add,
    current_trace,
    enabled,
    new_query_id,
    sample_qid,
    set_enabled,
    span,
    trace,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "registry", "DEFAULT_RING", "QidSampler", "QueryTrace", "Span",
    "TraceRing", "add", "attrib", "current_trace", "enabled",
    "new_query_id", "operators", "sample_qid", "set_enabled", "span",
    "trace",
]
