"""Session-serving decode workloads — batched autoregressive steps.

The stateful-serving path (``serve/sessions.py``) turns the one-shot
analytics models in this package into INTERACTIVE workloads: a client
opens a session, the session's recurrent/KV state stays resident in
the device cache between requests, and every ``GENERATE`` advances it
by one (or a few) decode steps. Per *Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching* (arxiv 2603.09555),
the decode loop wants exactly two disciplines:

* **One compiled step program shared by all concurrent sessions.**
  Concurrent ``GENERATE`` requests for the same model coalesce into a
  single padded batch; batch sizes quantize onto the
  ``plan/staging.bucket_rows`` ladder, so batch churn between 1 and
  ``decode_batch_max`` live sessions re-dispatches a cached executable
  instead of retracing. :func:`decode_stats`'s ``traces`` counter is
  the proof — the sessions bench pins it to the number of distinct
  (model-shape, bucket) pairs.
* **O(1) per-step state.** The LSTM carries ``(h, c)``; the
  transformer layer carries a RING-BUFFER KV cache of fixed
  ``kv_max`` entries (position writes at ``pos % kv_max`` — the
  portable O(1) cache: step cost never grows with sequence length).

Every step function is ROW-INDEPENDENT: row ``i`` of the output
depends only on row ``i`` of the inputs and the (shared) weights, so
a session decoded inside a padded batch of 8 produces bit-identical
outputs to the same session decoded alone — the byte-equality gate
``bench.py --sessions`` enforces, and the property that lets HA
followers replay mirrored GENERATE frames solo yet converge on the
leader's exact state.

**Multi-model residency** (``config.model_dedup``): model-set ingest
here is the serve-path consumer of the ``dedup/`` package. Each
registered model's weight pages are fingerprinted with
``dedup.detector.block_fingerprints``; once two models of the same
block class are registered, the sets pool through
``Client.dedup_resident`` → ``SetStore.set_pooled`` — byte-identical
pages resident ONCE under a shared device pool, fine-tuned variants
paying only for their deltas — while :meth:`DecodeRuntime.
residency_report` splits every shared page's bytes across its
referents (``page_bytes / refcount``) so per-client attribution stays
exact: the charges sum to the pool, and no client ever pays for
another's private pages.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.dedup import detector as _detector
from netsdb_tpu.plan.staging import bucket_rows
from netsdb_tpu.utils.locks import TrackedLock

#: decode model kinds the runtime can drive. "lstm" reuses the
#: recurrent cell family of ``ops/lstm.py`` (dense, batched);
#: "transformer_layer" is one attention+FFN layer with a ring-buffer
#: KV cache (``models/transformer.py``'s shape, O(1) per step).
DECODE_KINDS = ("lstm", "transformer_layer")

#: weight set names per kind — one store set per tensor, so the dedup
#: detector sees every fine-tuned variant's pages as ordinary
#: BlockedTensor blocks.
LSTM_WEIGHTS = ("w_i", "w_f", "w_c", "w_o",
                "u_i", "u_f", "u_c", "u_o",
                "b_i", "b_f", "b_c", "b_o")
TRANSFORMER_WEIGHTS = ("wq", "wk", "wv", "wo", "w1", "w2")

# process-global compiled-step cache + counters, the
# ``plan/executor.compile_stats`` idiom: ONE map of jitted step
# programs keyed (kind, shape signature, bucket), and monotonic
# counters the trace-pinning gates read. (serve/ cannot host this —
# the scatter-jit-route rule keeps compile caches out of the serve
# layer — so the decode programs live with the models they serve.)
_programs: Dict[Tuple, Callable] = {}
_stats = {"traces": 0, "programs": 0, "batches": 0, "steps": 0,
          "pad_rows": 0}
_mu = threading.Lock()


def decode_stats() -> Dict[str, int]:
    """Snapshot of the decode compile cache — ``traces`` counts actual
    jit traces (the one-program-per-bucket proof), ``batches``/
    ``steps``/``pad_rows`` the coalescing efficiency."""
    with _mu:
        out = dict(_stats)
    out["programs"] = len(_programs)
    return out


def clear_decode_programs() -> None:
    """Drop every cached step program and zero the counters (test
    isolation — mirrors ``plan/executor.clear_compiled_cache``)."""
    with _mu:
        _programs.clear()
        for k in _stats:
            _stats[k] = 0


obs.REGISTRY.register_collector("decode", decode_stats)


def decode_bucket(n: int) -> int:
    """The padded batch size for ``n`` concurrent sessions — the
    ``bucket_rows`` ladder (floor 8, {2^k, 3·2^(k-1)} rungs), so live
    session counts churning 1..8 all land on ONE program and growth
    past 8 adds at most O(log) more."""
    return bucket_rows(int(n))


def _program(key: Tuple, build: Callable) -> Callable:
    """The jitted step program for ``key``, tracing at most once per
    key for the process lifetime. The trace counter ticks inside the
    traced python body — it runs at trace time only, so ``traces``
    counts compilations, not dispatches."""
    fn = _programs.get(key)
    if fn is None:
        import jax

        def traced(*args, _inner=build):
            with _mu:
                _stats["traces"] += 1
            return _inner(*args)

        with _mu:
            fn = _programs.get(key)
            if fn is None:
                fn = jax.jit(traced)
                _programs[key] = fn
    return fn


# --- step functions (row-independent by construction) -----------------

def _lstm_step(params, h, c, x):
    """One batched LSTM cell step: ``(B, hidden) x (B, in)`` →
    ``(h', c')``. Dense weights (``w``: hidden×in, ``u``:
    hidden×hidden, ``b``: hidden) — the ops/lstm.py gate algebra on a
    session batch axis."""
    import jax.numpy as jnp

    def gate(name, act):
        z = (x @ params["w_" + name].T + h @ params["u_" + name].T
             + params["b_" + name])
        return act(z)

    import jax.nn as jnn
    i = gate("i", jnn.sigmoid)
    f = gate("f", jnn.sigmoid)
    g = gate("c", jnp.tanh)
    o = gate("o", jnn.sigmoid)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _transformer_step(params, k_cache, v_cache, pos, x, heads):
    """One batched transformer-layer decode step with a ring-buffer KV
    cache: write this step's k/v at ``pos % kv_max`` per row, attend
    over the ``min(pos+1, kv_max)`` live entries, add the FFN. All
    ops are per-row (matmuls, one-hot scatter, masked softmax), so
    batch composition never changes any single session's bits."""
    import jax.nn as jnn
    import jax.numpy as jnp

    kv_max = k_cache.shape[1]
    embed = x.shape[-1]
    dh = embed // heads
    q = x @ params["wq"].T
    k = x @ params["wk"].T
    v = x @ params["wv"].T
    # ring-buffer write: one-hot over the slot axis per row
    slot = pos % kv_max  # (B,)
    onehot = (jnp.arange(kv_max)[None, :] == slot[:, None])  # (B, T)
    k_cache2 = jnp.where(onehot[:, :, None], k[:, None, :], k_cache)
    v_cache2 = jnp.where(onehot[:, :, None], v[:, None, :], v_cache)
    live = jnp.minimum(pos + 1, kv_max)  # (B,) valid cache entries
    mask = jnp.arange(kv_max)[None, :] < live[:, None]  # (B, T)
    qh = q.reshape(-1, heads, dh)
    kh = k_cache2.reshape(-1, kv_max, heads, dh)
    vh = v_cache2.reshape(-1, kv_max, heads, dh)
    scores = jnp.einsum("bhd,bthd->bht", qh, kh) / jnp.sqrt(
        jnp.asarray(dh, x.dtype))
    scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
    attn = jnn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bht,bthd->bhd", attn, vh).reshape(-1, embed)
    y = x + ctx @ params["wo"].T
    ff = jnn.relu(y @ params["w1"].T) @ params["w2"].T
    return k_cache2, v_cache2, pos + 1, y + ff


# --- model deployment (the ingest path the dedup detector watches) ----

def _gen_dense(kind: str, hidden: int, heads: int,
               rng: "np.random.Generator") -> Dict[str, np.ndarray]:
    scale = 1.0 / np.sqrt(hidden)
    out: Dict[str, np.ndarray] = {}
    if kind == "lstm":
        for name in LSTM_WEIGHTS:
            if name.startswith("b_"):
                out[name] = np.zeros((hidden, 1), np.float32)
            else:
                out[name] = (rng.standard_normal((hidden, hidden))
                             * scale).astype(np.float32)
    else:
        ffn = 2 * hidden
        for name in ("wq", "wk", "wv", "wo"):
            out[name] = (rng.standard_normal((hidden, hidden))
                         * scale).astype(np.float32)
        out["w1"] = (rng.standard_normal((ffn, hidden))
                     * scale).astype(np.float32)
        out["w2"] = (rng.standard_normal((hidden, ffn))
                     * scale).astype(np.float32)
    return out


def deploy_decode_model(client, db: str, *, kind: str = "lstm",
                        hidden: int = 64, heads: int = 4,
                        seed: int = 0, base_seed: Optional[int] = None,
                        finetune_frac: float = 0.25,
                        block: Tuple[int, int] = (32, 32)) -> Dict:
    """Create ``db`` and load one decode model's weight sets.

    ``base_seed`` models FINE-TUNING: weights generate from the base
    seed, then ``finetune_frac`` of each tensor's block-grid tiles
    (chosen by ``seed``) are perturbed — two variants deployed from
    one base share exactly ``1 - finetune_frac`` of their weight
    pages bit-identically, the sharing the dedup detector collapses.
    Returns the model spec the server's SESSION_OPEN consumes."""
    if kind not in DECODE_KINDS:
        raise ValueError(f"kind must be one of {DECODE_KINDS}, "
                         f"got {kind!r}")
    rng = np.random.default_rng(base_seed if base_seed is not None
                                else seed)
    dense = _gen_dense(kind, hidden, heads, rng)
    if base_seed is not None:
        tune = np.random.default_rng(seed)
        for name, w in dense.items():
            if w.shape[1] == 1:
                continue  # biases stay shared
            bh, bw = block
            gh = max(1, w.shape[0] // bh)
            gw = max(1, w.shape[1] // bw)
            n_tiles = gh * gw
            picked = tune.choice(n_tiles,
                                 size=max(1, int(finetune_frac
                                                 * n_tiles)),
                                 replace=False)
            for t in picked:
                i, j = divmod(int(t), gw)
                w[i * bh:(i + 1) * bh, j * bw:(j + 1) * bw] += (
                    tune.standard_normal((min(bh, w.shape[0] - i * bh),
                                          min(bw, w.shape[1] - j * bw)))
                    * 0.01).astype(np.float32)
    client.create_database(db)
    for name, w in dense.items():
        client.create_set(db, name, type_name="matrix")
        shape = (block[0], 1) if w.shape[1] == 1 else tuple(block)
        client.send_matrix(db, name, w, block_shape=shape)
    return {"kind": kind, "hidden": int(hidden), "heads": int(heads)}


# --- the per-daemon decode runtime ------------------------------------

class DecodeRuntime:
    """Per-daemon model registry + batched step executor.

    Owns the device-resident weights of every registered decode model
    (assembled once from the store, shared-pooled when
    ``model_dedup``), and runs one padded, bucketed step program over
    a session batch. Stateless with respect to SESSIONS — per-session
    state lives in the devcache (``serve/sessions.py``); this class
    only maps ``(states, inputs) → (states', outputs)``."""

    def __init__(self, library, *, model_dedup: bool = False,
                 kv_max: int = 64, dedup_bands: int = 16):
        self._library = library
        self._model_dedup = bool(model_dedup)
        self._kv_max = int(kv_max)
        self._dedup_bands = int(dedup_bands)
        self._mu = TrackedLock("DecodeRuntime._mu")
        # db -> {"spec", "params" (device dense), "client",
        #        "fps" {(set, idx): hash}, "page_bytes" {hash: nbytes}}
        self._models: Dict[str, Dict[str, Any]] = {}
        self._dedup_report: Optional[Dict[str, Any]] = None

    # -- registration / residency -------------------------------------
    def register_model(self, db: str, kind: str,
                       client: Optional[str] = None,
                       heads: Optional[int] = None) -> Dict[str, Any]:
        """Load ``db``'s weight sets device-resident (idempotent).
        Fingerprints every weight page with ``dedup.detector``; with
        ``model_dedup`` on and a second model of the same class
        registered, re-pools ALL registered models' sets through
        ``Client.dedup_resident`` so shared pages install once."""
        import jax.numpy as jnp

        with self._mu:
            reg = self._models.get(db)
            if reg is not None:
                return reg["spec"]
        if kind not in DECODE_KINDS:
            raise ValueError(f"unknown decode kind {kind!r}")
        names = LSTM_WEIGHTS if kind == "lstm" else TRANSFORMER_WEIGHTS
        tensors = {n: self._library.get_tensor(db, n) for n in names}
        fps: Dict[Tuple[str, tuple], str] = {}
        page_bytes: Dict[str, int] = {}
        for n, t in tensors.items():
            for idx, h in _detector.block_fingerprints(t).items():
                fps[(n, idx)] = h
                bh, bw = t.meta.block_shape
                page_bytes[h] = bh * bw * t.data.dtype.itemsize
        hidden = tensors[names[0]].meta.shape[0]
        spec = {"kind": kind, "hidden": int(hidden),
                "heads": int(heads or 4), "kv_max": self._kv_max}
        params = {n: jnp.asarray(t.data[:t.meta.shape[0],
                                        :t.meta.shape[1]])
                  for n, t in tensors.items()}
        if kind == "lstm":
            for b in ("b_i", "b_f", "b_c", "b_o"):
                params[b] = params[b].reshape(-1)
        with self._mu:
            self._models[db] = {"spec": spec, "params": params,
                                "client": client, "fps": fps,
                                "page_bytes": page_bytes}
            pool_now = (self._model_dedup and len(self._models) > 1)
            dbs = list(self._models)
        if pool_now:
            sets = [(d, n) for d in dbs
                    for n in self._weight_names(d)]
            report = self._library.dedup_resident(
                sets, bands=self._dedup_bands)
            with self._mu:
                self._dedup_report = report
            obs.REGISTRY.gauge("dedup.page_bytes").set(
                int(report.get("hbm_bytes_pooled", 0)))
        return spec

    def _weight_names(self, db: str) -> Sequence[str]:
        kind = self._models[db]["spec"]["kind"]
        return LSTM_WEIGHTS if kind == "lstm" else TRANSFORMER_WEIGHTS

    def spec(self, db: str) -> Optional[Dict[str, Any]]:
        with self._mu:
            reg = self._models.get(db)
            return dict(reg["spec"]) if reg else None

    def drop_model(self, db: str) -> bool:
        with self._mu:
            return self._models.pop(db, None) is not None

    def residency_report(self) -> Dict[str, Any]:
        """Exact multi-model residency accounting. ``charged`` splits
        every page's bytes across the models referencing it
        (``page_bytes / refcount``) and rolls up per client — the
        charges sum to the unique-page total, so attribution stays
        exact under any degree of sharing."""
        with self._mu:
            refs: Dict[str, int] = {}
            for reg in self._models.values():
                for h in set(reg["fps"].values()):
                    refs[h] = refs.get(h, 0) + 1
            charged: Dict[str, float] = {}
            by_model: Dict[str, float] = {}
            unique_bytes = 0
            sized: Dict[str, int] = {}
            for reg in self._models.values():
                sized.update(reg["page_bytes"])
            for h, n in refs.items():
                unique_bytes += sized.get(h, 0)
            for db, reg in self._models.items():
                share = sum(sized.get(h, 0) / refs[h]
                            for h in set(reg["fps"].values()))
                by_model[db] = share
                who = reg.get("client") or db
                charged[who] = charged.get(who, 0.0) + share
            out = {
                "models": len(self._models),
                "unique_page_bytes": int(unique_bytes),
                "total_page_bytes": int(sum(
                    sum(sized.get(h, 0)
                        for h in set(reg["fps"].values()))
                    for reg in self._models.values())),
                "charged_bytes": {k: int(round(v))
                                  for k, v in charged.items()},
                "charged_by_model": {k: int(round(v))
                                     for k, v in by_model.items()},
                "model_dedup": self._model_dedup,
            }
            if self._dedup_report is not None:
                out["pool"] = dict(self._dedup_report)
        return out

    # -- state ---------------------------------------------------------
    def state_layers(self, db: str) -> Dict[str, Tuple]:
        """{layer name: shape} of one session's state for ``db``."""
        spec = self.spec(db)
        if spec is None:
            raise KeyError(db)
        h = spec["hidden"]
        if spec["kind"] == "lstm":
            return {"h": (h,), "c": (h,)}
        return {"k": (spec["kv_max"], h), "v": (spec["kv_max"], h),
                "pos": ()}

    def init_state(self, db: str) -> Dict[str, np.ndarray]:
        out = {}
        for layer, shape in self.state_layers(db).items():
            dtype = np.int32 if layer == "pos" else np.float32
            out[layer] = np.zeros(shape, dtype)
        return out

    def state_nbytes(self, db: str) -> int:
        return sum(int(np.prod(s or (1,))) * 4
                   for s in self.state_layers(db).values())

    # -- the batched step ----------------------------------------------
    def step_batch(self, db: str,
                   states: List[Dict[str, Any]],
                   xs: List[Any]
                   ) -> Tuple[List[Dict[str, Any]], List[np.ndarray]]:
        """Advance ``len(states)`` sessions of one model by ONE step in
        a single padded program dispatch. Returns per-session new
        states (device arrays) and outputs (host). Row independence
        makes the result per session bit-equal to a solo run."""
        import jax.numpy as jnp

        with self._mu:
            reg = self._models.get(db)
        if reg is None:
            raise KeyError(f"model {db!r} not registered")
        spec = reg["spec"]
        params = reg["params"]
        n = len(states)
        bucket = decode_bucket(n)
        pad = bucket - n
        hidden = spec["hidden"]

        def stack(layer, shape, dtype=np.float32):
            rows = [np.asarray(s[layer], dtype) for s in states]
            rows += [np.zeros(shape, dtype)] * pad
            return jnp.asarray(np.stack(rows))

        x = jnp.asarray(np.stack(
            [np.asarray(v, np.float32) for v in xs]
            + [np.zeros((hidden,), np.float32)] * pad))
        if spec["kind"] == "lstm":
            key = ("lstm", hidden, bucket)
            fn = _program(key, _lstm_step)
            h2, c2 = fn(params, stack("h", (hidden,)),
                        stack("c", (hidden,)), x)
            new = [{"h": h2[i], "c": c2[i]} for i in range(n)]
            outs = [np.asarray(h2[i]) for i in range(n)]
        else:
            kv = spec["kv_max"]
            heads = spec["heads"]
            key = ("transformer_layer", hidden, kv, heads, bucket)
            fn = _program(
                key, lambda p, kc, vc, pos, xx:
                _transformer_step(p, kc, vc, pos, xx, heads))
            k2, v2, pos2, y = fn(
                params, stack("k", (kv, hidden)),
                stack("v", (kv, hidden)),
                stack("pos", (), np.int32), x)
            new = [{"k": k2[i], "v": v2[i], "pos": pos2[i]}
                   for i in range(n)]
            outs = [np.asarray(y[i]) for i in range(n)]
        with _mu:
            _stats["batches"] += 1
            _stats["steps"] += n
            _stats["pad_rows"] += pad
        return new, outs
