"""Word2vec embedding serving in the database.

Mirrors the reference word2vec workload (``src/word2vec/source/
Word2Vec.cc:19-80``): an embedding matrix set is scanned and multiplied
against one-hot input rows via ``FFTransposeMult``+``FFAggMatrix``. The
TPU build serves both formulations: the relational matmul DAG (what the
planner produces) and the gather path (what a TPU should run), plus the
sparse segment-combined variant (``EmbeddingLookupSparse.h``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import embedding as emb_ops
from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet


class Word2VecModel:
    SETS = ("weights", "inputs", "output")

    def __init__(self, db: str = "w2v", block: Tuple[int, int] = (512, 512),
                 compute_dtype: Optional[str] = None):
        self.db = db
        self.block = block
        self.compute_dtype = compute_dtype

    def setup(self, client: Client, placements=None) -> None:
        """``placements`` maps set name → Placement (the createSet-time
        PartitionPolicy): with ``weights`` row- or column-sharded and
        ``inputs`` batch-sharded, the SAME inference DAG and gather
        paths run distributed — the executor's jit sees the stored
        shardings and XLA inserts the collectives
        (``QuerySchedulerServer.cc:216-330``)."""
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s))

    def load_embeddings(self, client: Client, table: np.ndarray) -> None:
        """``table``: (vocab x dim)."""
        client.send_matrix(self.db, "weights", table, self.block)

    def load_onehot_inputs(self, client: Client, ids: np.ndarray,
                           vocab: int) -> None:
        onehot = np.asarray(emb_ops.one_hot_matrix(np.asarray(ids), vocab))
        client.send_matrix(self.db, "inputs", onehot, self.block)

    def build_inference_dag(self) -> WriteSet:
        """Relational form: onehot ⋈ weights matmul (Word2Vec.cc shape)."""
        cd = self.compute_dtype
        w = ScanSet(self.db, "weights")
        x = ScanSet(self.db, "inputs")
        out = Join(x, w, fn=lambda o, t: emb_ops.embedding_matmul(t, o, cd),
                   label="FFTransposeMult")
        return WriteSet(out, self.db, "output")

    def inference(self, client: Client) -> BlockedTensor:
        res = client.execute_computations(self.build_inference_dag(),
                                          job_name=f"{self.db}-inference")
        return next(iter(res.values()))

    def lookup(self, client: Client, ids: np.ndarray) -> jax.Array:
        """Gather path — no one-hot materialization."""
        return emb_ops.embedding_lookup(
            client.get_tensor(self.db, "weights"), np.asarray(ids))

    def lookup_sparse(self, client: Client, ids, segment_ids, num_segments,
                      combiner: str = "mean") -> jax.Array:
        return emb_ops.embedding_lookup_sparse(
            client.get_tensor(self.db, "weights"), np.asarray(ids),
            np.asarray(segment_ids), num_segments, combiner)
