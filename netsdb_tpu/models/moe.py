"""Mixture-of-experts layer with expert parallelism.

No reference analogue (netsDB has no experts, SURVEY §2.6 row
"TP/SP/EP … absent"); added so the framework's parallelism taxonomy is
complete. Top-1 token routing with a capacity limit, the classic
dispatch/combine einsum formulation: dispatch (tokens→expert slots) and
combine (expert outputs→tokens) are one-hot tensors, so expert compute
is dense batched matmuls on the MXU, and sharding the EXPERT dimension
over a mesh axis makes XLA insert the token all-to-alls — expert
parallelism without hand-written routing collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_HI = jax.lax.Precision.HIGHEST


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MoEParams:
    w_gate: jax.Array  # (d, n_experts)
    w_up: jax.Array    # (n_experts, d, hidden)
    w_down: jax.Array  # (n_experts, hidden, d)


def init_moe_params(d: int, hidden: int, n_experts: int,
                    seed: int = 0) -> MoEParams:
    rng = np.random.default_rng(seed)
    return MoEParams(
        w_gate=jnp.asarray(rng.standard_normal((d, n_experts)),
                           jnp.float32) * d ** -0.5,
        w_up=jnp.asarray(rng.standard_normal((n_experts, d, hidden)),
                         jnp.float32) * d ** -0.5,
        w_down=jnp.asarray(rng.standard_normal((n_experts, hidden, d)),
                           jnp.float32) * hidden ** -0.5,
    )


def moe_forward(params: MoEParams, x: jax.Array,
                capacity_factor: float = 2.0,
                mesh: Optional[Mesh] = None,
                expert_axis: str = "model") -> jax.Array:
    """x: (tokens, d) → (tokens, d). Tokens over an expert's capacity are
    dropped (standard top-1 switch behavior). With ``mesh``, expert-dim
    tensors are sharding-constrained to ``expert_axis`` (EP)."""
    tokens, d = x.shape
    n_experts = params.w_gate.shape[1]
    capacity = max(1, int(capacity_factor * tokens / n_experts))

    logits = jnp.einsum("td,de->te", x, params.w_gate, precision=_HI)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)            # (tokens,)
    gate = jnp.max(probs, axis=-1)                     # (tokens,)

    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    position = jnp.cumsum(onehot, axis=0) * onehot - 1  # (tokens, experts)
    pos_in_expert = position.max(axis=-1)
    keep = pos_in_expert < capacity

    # dispatch: (tokens, experts, capacity) one-hot
    dispatch = (jax.nn.one_hot(expert_idx, n_experts, dtype=x.dtype)[:, :, None]
                * jax.nn.one_hot(pos_in_expert, capacity, dtype=x.dtype)[:, None, :])
    dispatch = dispatch * keep[:, None, None].astype(x.dtype)
    combine = dispatch * gate[:, None, None].astype(x.dtype)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x, precision=_HI)
    if mesh is not None:
        spec = NamedSharding(mesh, P(expert_axis, None, None))
        expert_in = jax.lax.with_sharding_constraint(expert_in, spec)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, params.w_up,
                               precision=_HI))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params.w_down, precision=_HI)
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(expert_axis, None, None)))
    return jnp.einsum("tec,ecd->td", combine, expert_out, precision=_HI)


def moe_forward_dense_oracle(params: MoEParams, x: jax.Array,
                             capacity_factor: float = 2.0) -> jax.Array:
    """Reference implementation: loop over tokens in Python — used only
    by tests to validate routing/capacity semantics."""
    tokens, d = x.shape
    n_experts = params.w_gate.shape[1]
    capacity = max(1, int(capacity_factor * tokens / n_experts))
    logits = np.asarray(x @ params.w_gate)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    out = np.zeros_like(np.asarray(x))
    counts = np.zeros(n_experts, np.int64)
    for t in range(tokens):
        e = int(probs[t].argmax())
        if counts[e] >= capacity:
            counts[e] += 1  # token dropped (position past capacity)
            continue
        counts[e] += 1
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            np.asarray(x[t]) @ np.asarray(params.w_up[e]))))
        y = h @ np.asarray(params.w_down[e])
        out[t] = probs[t, e] * y
    return jnp.asarray(out)
