"""Text classification: word2vec front end + semantic-classifier layer.

Mirrors the reference text-classification pipeline
(``model-inference/text-classification/README.md:1-37``; driver
``src/word2vec/source/TestSemanticClassifier.cc``): layer 1 is the
word2vec embedding matmul, layer 2 is ``SemanticClassifier`` — an entire
FC layer (weights + bias + softmax) encapsulated in one UDF
(``src/word2vec/headers/SemanticClassifier.h``). Here layer 2 is one
traced function for the same reason the reference fused it: it avoids a
shuffle between layers — XLA fuses it into the embedding matmul.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import embedding as emb_ops
from netsdb_tpu.ops import nn as nn_ops
from netsdb_tpu.ops.matmul import matmul_t
from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet


class TextClassifierModel:
    SETS = ("embeddings", "inputs", "fc_w", "fc_b", "output")

    def __init__(self, db: str = "textcls", block: Tuple[int, int] = (512, 512),
                 compute_dtype: Optional[str] = None):
        self.db = db
        self.block = block
        self.compute_dtype = compute_dtype

    def setup(self, client: Client) -> None:
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s)

    def load_weights(self, client: Client, embeddings: np.ndarray,
                     fc_w: np.ndarray, fc_b: np.ndarray) -> None:
        """``embeddings``: (vocab x dim); ``fc_w``: (classes x dim);
        ``fc_b``: (classes,)."""
        client.send_matrix(self.db, "embeddings", embeddings, self.block)
        client.send_matrix(self.db, "fc_w", fc_w, self.block)
        client.send_matrix(self.db, "fc_b",
                           np.asarray(fc_b).reshape(-1, 1),
                           (self.block[0], 1))

    def load_onehot_inputs(self, client: Client, ids: np.ndarray,
                           vocab: int) -> None:
        onehot = np.asarray(emb_ops.one_hot_matrix(np.asarray(ids), vocab))
        client.send_matrix(self.db, "inputs", onehot, self.block)

    def semantic_classifier(self, feats: BlockedTensor, w: BlockedTensor,
                            b: BlockedTensor) -> BlockedTensor:
        """The whole-FC-layer UDF: softmax(W·featsᵀ + b) over classes.
        ``feats``: (batch x dim) → output (classes x batch)."""
        z = matmul_t(w, feats, self.compute_dtype)
        return nn_ops.ff_output_layer(z, b, axis=0)

    def build_inference_dag(self) -> WriteSet:
        cd = self.compute_dtype
        emb = ScanSet(self.db, "embeddings")
        x = ScanSet(self.db, "inputs")
        w = ScanSet(self.db, "fc_w")
        b = ScanSet(self.db, "fc_b")
        feats = Join(x, emb, fn=lambda o, t: emb_ops.embedding_matmul(t, o, cd),
                     label="Word2Vec")
        z = Join(w, feats, fn=lambda ww, ff: matmul_t(ww, ff, cd),
                 label="SemanticClassifierMatmul")
        probs = Join(z, b, fn=lambda zz, bb: nn_ops.ff_output_layer(zz, bb, axis=0),
                     label="SemanticClassifierSoftmax")
        return WriteSet(probs, self.db, "output")

    def inference(self, client: Client) -> BlockedTensor:
        res = client.execute_computations(self.build_inference_dag(),
                                          job_name=f"{self.db}-inference")
        return next(iter(res.values()))

    def classify_bag_of_words(self, client: Client, token_ids, segment_ids,
                              num_docs: int) -> jax.Array:
        """Sparse path: per-document mean embedding → FC layer → argmax.
        (reference EmbeddingLookupSparse front end)."""
        feats = emb_ops.embedding_lookup_sparse(
            client.get_tensor(self.db, "embeddings"), np.asarray(token_ids),
            np.asarray(segment_ids), num_docs, "mean")  # (docs x dim)
        fb = BlockedTensor.from_dense(feats, self.block)
        probs = self.semantic_classifier(
            fb, client.get_tensor(self.db, "fc_w"),
            client.get_tensor(self.db, "fc_b"))
        return probs.to_dense().argmax(axis=0)
