"""Conv2D model serving — both reference modes as database workloads.

Mode "direct" mirrors ``src/conv2d_proj`` (driver ``src/tests/source/
Conv2dProjTest.cc``): images as rank-4 tensors in a set, one Selection
applying the conv per tensor (ATen there, ``lax.conv_general_dilated``
here). Mode "im2col" mirrors ``src/conv2d_memory_fusion`` (driver
``PipelinedConv2dMemFuseTest.cc:137-299``): the relational
image→chunks→matrix→matmul→image rewrite, here the explicit-patches +
blocked-matmul pipeline. Reference default shapes: 112x112x3 images,
64 7x7x3 filters (``model-inference/convolutional-neural-network/
README.md:8-16``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import conv as conv_ops
from netsdb_tpu.plan.computations import Apply, Join, ScanSet, WriteSet


class Conv2DModel:
    SETS = ("images", "kernels", "bias", "output")

    def __init__(self, db: str = "conv", mode: str = "direct",
                 stride: Tuple[int, int] = (1, 1), padding="VALID",
                 activation: Optional[str] = None,
                 block: Tuple[int, int] = (256, 256),
                 compute_dtype: Optional[str] = None):
        if mode not in ("direct", "im2col"):
            raise ValueError(f"unknown conv mode {mode!r}")
        self.db = db
        self.mode = mode
        self.stride = stride
        self.padding = padding
        self.activation = activation
        self.block = block
        self.compute_dtype = compute_dtype

    def setup(self, client: Client) -> None:
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s, type_name="tensor4d")

    def load(self, client: Client, images: np.ndarray, kernels: np.ndarray,
             bias: Optional[np.ndarray] = None) -> None:
        """images (N,C,H,W); kernels (O,I,KH,KW); bias (O,). Rank-4
        tensors are stored as raw arrays (reference ``TensorData``
        N-rank type, ``src/conv2d_proj/headers/TensorData.h``)."""
        client.send_data(self.db, "images", [np.asarray(images, np.float32)])
        client.send_data(self.db, "kernels", [np.asarray(kernels, np.float32)])
        if bias is not None:
            client.send_data(self.db, "bias", [np.asarray(bias, np.float32)])

    def _conv(self, images, kernels, bias, activation):
        kw = dict(stride=self.stride, padding=self.padding,
                  activation=activation, compute_dtype=self.compute_dtype)
        if self.mode == "direct":
            return conv_ops.conv2d_direct(images, kernels, bias, **kw)
        return conv_ops.conv2d_im2col(images, kernels, bias,
                                      block_shape=self.block, **kw)

    def build_inference_dag(self) -> WriteSet:
        images = ScanSet(self.db, "images")
        kernels = ScanSet(self.db, "kernels")
        bias = ScanSet(self.db, "bias")

        def apply_conv(img_items, ker_items):
            # conv only; bias + activation joined in downstream
            return [self._conv(img, ker_items[0], None, None)
                    for img in img_items]

        def bias_act(conv_items, bias_items):
            import jax.nn as jnn

            b = bias_items[0] if bias_items else None
            out = []
            for c in conv_items:
                if b is not None:
                    c = c + b.reshape(1, -1, 1, 1)
                if self.activation == "relu":
                    c = jnn.relu(c)
                elif self.activation == "sigmoid":
                    c = jnn.sigmoid(c)
                out.append(c)
            return out

        conv = Join(images, kernels, fn=apply_conv,
                    label="Conv2DSelect" if self.mode == "direct"
                    else "ConvMemoryFusion")
        out = Join(conv, bias, fn=bias_act, label="KernelBiasJoin")
        return WriteSet(out, self.db, "output")

    def inference(self, client: Client):
        """Run conv over every image tensor in the images set."""
        res = client.execute_computations(self.build_inference_dag(),
                                          job_name=f"{self.db}-{self.mode}")
        return next(iter(res.values()))
