"""Model serving over the sharded daemon pool — distributed inference.

The reference serves model inference by storing the model as blocked
matrix sets and scoring batches through the relational engine
(``SimpleFF.cc`` + ``QueryClient.h:160-224``: many query clients, one
loaded model). This module is that pattern over the horizontal
scale-out pool (``serve/shard.py``), in three pieces:

* **model-as-blocked-sets ingest** — :meth:`ModelServing.deploy`
  creates the batch-partitioned input tensor set
  (``placement="range"``) on the pool leader and mirrors the model's
  weight sets onto EVERY pool member: weights replicated, activations
  data-parallel by batch — the canonical inference-serving placement.
* **layer-chain plan builder** — the model's inference DAG is built
  against the served input/output sets and stamped with the
  ``scatter_gather`` declaration that opts it into the
  ``tensor_chain`` scatter kind (``plan/scatter.py``): each shard then
  executes the WHOLE chain over its local batch partition through its
  own executor, which compiles it as ONE program per shard — the
  whole-plan jit for resident weight sets (every EXPLAIN node marked
  ``fused``), the region mapper (``plan/fusion.py``) when weights are
  ``storage="paged"`` and must stream.
* **batched scoring frames** — :meth:`ModelServing.score` routed-
  ingests one batch (contiguous row slices to the owning shards, in
  parallel) and executes the chain pool-wide; the coordinator
  concatenates per-shard outputs in slot order, byte-equal to a
  single-daemon run (every output element is computed from exactly
  one shard's rows, never summed across shards).

``explain=True`` scoring returns the per-layer EXPLAIN decomposition:
the coordinator slot's annotated operator tree plus the full
per-shard forest, every node marked with the daemon that executed it
— what ``bench.py --serve``'s ``ff_inference_rows_per_sec_per_chip``
headline renders.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu import obs


class ModelServing:
    """Serve one layer-chain model (FF-style: ``build_inference_dag``
    + a ``db``/``block`` surface) over a leader + shard-worker pool.

    ``batch_axis`` is the axis of the model's OUTPUT along which the
    batch runs (1 for FF's ``(labels x batch)`` activations);
    ``gather_mode="items"`` instead concatenates per-shard item LISTS
    (the conv2d shape — one output tensor per input image).
    ``sink_builder`` overrides the default
    ``model.build_inference_dag(input_set=..., output_set=...)`` for
    models whose builder takes no set arguments."""

    def __init__(self, model, leader_addr: str,
                 input_set: str = "inputs", output_set: str = "output",
                 batch_axis: int = 1, gather_mode: str = "concat",
                 block: Optional[Tuple[int, int]] = None,
                 sink_builder: Optional[Callable[[], Any]] = None):
        self.model = model
        self.leader_addr = leader_addr
        self.input_set = input_set
        self.output_set = output_set
        self.batch_axis = int(batch_axis)
        self.gather_mode = gather_mode
        self.block = tuple(block) if block is not None \
            else tuple(getattr(model, "block", ()) or ()) or None
        self.sink_builder = sink_builder
        self.addrs: List[str] = []
        self._leader = None

    # --- lifecycle ----------------------------------------------------
    def _client(self):
        if self._leader is None:
            from netsdb_tpu.serve.client import RemoteClient

            self._leader = RemoteClient(self.leader_addr)
        return self._leader

    def close(self) -> None:
        if self._leader is not None:
            self._leader.close()
            self._leader = None

    def deploy(self, load_model: Callable[[Any], None]) -> List[str]:
        """Model-as-blocked-sets ingest: create the batch-partitioned
        input set on the leader (one slot per pool member), then run
        ``load_model(client)`` against EVERY member — each daemon ends
        up holding the full weight sets locally, which is exactly what
        the tensor_chain subplan's weight ScanSets read shard-side.
        ``load_model`` is typically ``model.setup`` + weight loading;
        set creation is idempotent, so re-deploy refreshes weights in
        place. Returns the pool's slot addresses in slot order."""
        from netsdb_tpu.serve.client import RemoteClient

        c = self._client()
        db = self.model.db
        c.create_database(db)
        c.create_set(db, self.input_set, type_name="tensor",
                     placement="range")
        entry = c._placement_entry(db, self.input_set, refresh=True)
        addrs = [sl["addr"] for sl in entry["slots"]]
        for addr in addrs:
            wc = RemoteClient(addr)
            try:
                load_model(wc)
            finally:
                wc.close()
        self.addrs = addrs
        obs.REGISTRY.counter("models.deploys").inc()
        return addrs

    # --- the layer-chain plan ----------------------------------------
    def _sink(self):
        if self.sink_builder is not None:
            sink = self.sink_builder()
        else:
            sink = self.model.build_inference_dag(
                input_set=self.input_set, output_set=self.output_set)
        # the tensor_chain opt-in: declares the chain batch-
        # decomposable along `axis` (plan/scatter.py module docstring)
        sink.scatter_gather = {"axis": self.batch_axis,
                               "block": self.block,
                               "mode": self.gather_mode}
        return sink

    # --- batched scoring ---------------------------------------------
    def score(self, batch, explain: bool = False):
        """One scoring frame: routed batch ingest + pool-wide chain
        execution. Returns the assembled output (a BlockedTensor when
        ``block`` is declared); with ``explain=True`` returns
        ``(output, shard_operators)`` — the per-shard EXPLAIN forest,
        every node annotated with its executing daemon."""
        from netsdb_tpu.serve.protocol import CODEC_PICKLE, MsgType

        c = self._client()
        db = self.model.db
        batch = np.asarray(batch, np.float32)
        t0 = time.perf_counter()
        c.send_matrix(db, self.input_set, batch, self.block)
        reply = c._request(
            MsgType.EXECUTE_COMPUTATIONS,
            {"sinks": [self._sink()], "job_name": f"{db}-serve",
             "materialize": True, "explain": bool(explain)},
            codec=CODEC_PICKLE)
        results = c._collect_results(reply["results"], True)
        value = next(iter(results.values()))
        rows = int(batch.shape[0])
        obs.REGISTRY.counter("models.batches_scored").inc()
        obs.REGISTRY.counter("models.rows_scored").inc(rows)
        obs.add("models.score_s", time.perf_counter() - t0)
        if explain:
            return value, reply.get("shard_operators")
        return value

    def score_batches(self, batches):
        """Score an iterable of batches in arrival order (the serving
        loop — one routed frame per batch over the same deployed
        pool)."""
        for batch in batches:
            yield self.score(batch)


def ff_serving(model, leader_addr: str, **kw) -> ModelServing:
    """FF convenience: batch runs along axis 1 of the ``(labels x
    batch)`` output; the model's own block shape re-blocks the
    assembly."""
    kw.setdefault("batch_axis", 1)
    return ModelServing(model, leader_addr, **kw)
