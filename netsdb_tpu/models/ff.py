"""Feed-forward NN inference in the database — the flagship workload.

Mirrors the reference FF application end to end
(``src/FF/source/SimpleFF.cc``, driver ``src/tests/source/FFTest.cc``):

- ``setup``/``create_sets`` ≙ ``ff::setup`` registering the 12 UDF .so
  libs + ``ff::createSet`` of {inputs, w1, b1, wo, bo, y1, yo, output}
  (``SimpleFF.cc:60-82``);
- ``load_random_weights`` ≙ ``ff::loadMatrix`` (random blocked matrices);
- ``inference`` ≙ ``ff::inference_unit`` (``SimpleFF.cc:331-424``):
  stage A  y1 = relu(w1·inputsᵀ + b1); yo = wo·y1 + bo
  stage B  output = softmax over labels (exp → row-sum → normalize);
- the DAG built here is scan→join→agg→map→write Computations, so the
  plan dump shows the same relational shape as the reference's TCAP.

Layout convention follows the reference: inputs are (batch x features),
weights (out x in), activations flow as (features x batch).

``train_step`` has no reference analogue as a fused op (netsDB trains
offline in TF/PyTorch and imports weights) but is required for the
multi-chip dry-run and completes the framework: cross-entropy + SGD via
``jax.grad`` over the same blocked tensors.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import nn as nn_ops
from netsdb_tpu.ops.matmul import matmul, matmul_t
from netsdb_tpu.plan.computations import Apply, Join, ScanSet, WriteSet


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FFParams:
    w1: BlockedTensor  # (hidden x features)
    b1: BlockedTensor  # (hidden x 1)
    wo: BlockedTensor  # (labels x hidden)
    bo: BlockedTensor  # (labels x 1)


class FFModel:
    """One-hidden-layer FF classifier stored as database sets."""

    SETS = ("inputs", "w1", "b1", "wo", "bo", "y1", "yo", "output")

    def __init__(self, db: str = "ff", block: Tuple[int, int] = (512, 512),
                 compute_dtype: Optional[str] = None):
        self.db = db
        self.block = block
        self.compute_dtype = compute_dtype

    # --- setup (ref ff::setup + createSet, SimpleFF.cc:60-82) ---------
    def setup(self, client: Client,
              placements: Optional[Dict[str, object]] = None,
              storages: Optional[Dict[str, str]] = None) -> None:
        """``placements`` maps set name → Placement: declare at createSet
        how each model set shards over the mesh (inputs/activations on
        ``data``, weight rows/cols on ``model``, biases replicated) —
        the reference's per-set PartitionPolicy, upgraded from "which
        worker" to "which mesh axis". Execution then distributes with no
        further client involvement: the executor's jit sees the stored
        shardings.

        ``storages`` maps set name → "memory"|"paged": weight sets
        declared ``paged`` live as arena pages and STREAM through the
        inference DAG (larger-than-HBM weights, the reference's
        storage-managed weight scans — ``SimpleFF.cc:94-290``)."""
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s),
                              storage=(storages or {}).get(s, "memory"))
        client.register_type("FFMatrixBlock", "netsdb_tpu.core.blocked:BlockedTensor")
        # a live placement advisor (client.set_placement_advisor) may
        # have chosen the block shape at create_set — adopt it so the
        # whole model blocks consistently with its sets' placement.
        # (RemoteClient has no local catalog; placement is decided
        # daemon-side there.)
        catalog = getattr(client, "catalog", None)
        if catalog is not None:
            placed = (catalog.get_set(self.db, "w1") or {}).get(
                "meta", {}).get("block_shape")
            if placed:
                self.block = tuple(placed)

    def load_weights(self, client: Client, w1, b1, wo, bo) -> None:
        br = self.block[0]
        client.send_matrix(self.db, "w1", w1, self.block)
        client.send_matrix(self.db, "b1", np.asarray(b1).reshape(-1, 1), (br, 1))
        client.send_matrix(self.db, "wo", wo, self.block)
        client.send_matrix(self.db, "bo", np.asarray(bo).reshape(-1, 1), (br, 1))

    def load_random_weights(self, client: Client, features: int, hidden: int,
                            labels: int, seed: int = 0) -> None:
        """ref ff::loadMatrix with random data (FFTest.cc:100-117)."""
        rng = np.random.default_rng(seed)
        scale1 = np.sqrt(2.0 / features)
        scale2 = np.sqrt(2.0 / hidden)
        self.load_weights(
            client,
            rng.standard_normal((hidden, features), dtype=np.float32) * scale1,
            rng.standard_normal((hidden,), dtype=np.float32) * 0.01,
            rng.standard_normal((labels, hidden), dtype=np.float32) * scale2,
            rng.standard_normal((labels,), dtype=np.float32) * 0.01,
        )

    def load_inputs(self, client: Client, inputs: np.ndarray) -> None:
        client.send_matrix(self.db, "inputs", inputs, self.block)

    # --- inference (ref ff::inference_unit, SimpleFF.cc:331-424) ------
    def build_inference_dag(self, dropout_rate: float = 0.0,
                            key: Optional[jax.Array] = None,
                            input_set: str = "inputs",
                            output_set: str = "output") -> WriteSet:
        """Computation DAG with the reference's relational shape.

        ``input_set``/``output_set`` let concurrent clients share the
        resident weight sets while scanning/writing private sets — the
        served-inference pattern (many QueryClients, one loaded model,
        reference ``QueryClient.h:160-224``)."""
        cd = self.compute_dtype
        inputs = ScanSet(self.db, input_set)
        w1 = ScanSet(self.db, "w1")
        b1 = ScanSet(self.db, "b1")
        wo = ScanSet(self.db, "wo")
        bo = ScanSet(self.db, "bo")
        # both weight matmuls are row-decomposable in the weight: when
        # the weight set is storage="paged", the executor streams its
        # row-block pages through the same fn and concatenates output
        # rows (out_block pins the assembled meta to the resident
        # path's) — the reference's page-fed weight scans
        # (SimpleFF.cc:94-290 + FFMatrixBlockScanner.h); resident sets
        # ignore the fold entirely
        from netsdb_tpu.plan.fold import TensorFold

        def _dense(v):
            return np.asarray(v.to_dense()) \
                if isinstance(v, BlockedTensor) else np.asarray(v)

        # the SUMMA declarations (fn(block, x) == block @ rhs(x)) make
        # both weight streams routable through the distributed engine
        # under config.distributed_matmul — declared ONLY under full-
        # precision compute: SUMMA's k-panel accumulation reassociates
        # the contraction (exact for f32 HIGHEST over integer-valued
        # operands, last-ulp for reduced precision epilogues)
        wfold = TensorFold(mode="rows",
                           out_block=(self.block[0], self.block[0]),
                           summa_rhs=(lambda x: _dense(x).T)
                           if cd is None else None)
        rfold = TensorFold(mode="rows",
                           out_block=(self.block[0], self.block[0]),
                           summa_rhs=(lambda y: _dense(y))
                           if cd is None else None)
        # FFTransposeMult + FFAggMatrix: w1 · inputsᵀ → (hidden x batch)
        h = Join(w1, inputs, fn=lambda w, x: matmul_t(w, x, cd,
                                                      accum_dtype=cd),
                 label="FFTransposeMult", tensor_fold=wfold)
        # FFReluBiasSum
        y1 = Join(h, b1,
                  fn=lambda hh, bb: nn_ops.bias_relu(hh, bb, dropout_rate, key),
                  label="FFReluBiasSum")
        # FFInputLayerJoin + FFAggMatrix: wo · y1 → (labels x batch)
        yo_lin = Join(wo, y1, fn=lambda w, y: matmul(w, y, cd),
                      label="FFInputLayerJoin", tensor_fold=rfold)
        # FFTransposeBiasSum → FFRowAggregate → FFOutputLayer, fused
        out = Join(yo_lin, bo,
                   fn=lambda y, b: nn_ops.ff_output_layer(y, b, axis=0),
                   label="FFOutputLayer")
        return WriteSet(out, self.db, output_set)

    def inference(self, client: Client, dropout_rate: float = 0.0,
                  key: Optional[jax.Array] = None) -> BlockedTensor:
        sink = self.build_inference_dag(dropout_rate, key)
        results = client.execute_computations(sink, job_name=f"{self.db}-inference")
        return next(iter(results.values()))

    def build_fused_inference_dag(self, params: "FFParams",
                                  out_mode: str = "softmax") -> WriteSet:
        """Whole network inside ONE computation — the reference's
        ``src/FF_proj`` variant (``FullyConnectedNetwork.h:18-127``): a
        single SelectionComp holding all weights as members, scanning
        only the input set. ``out_mode="label"`` mirrors FF_proj's head
        (sigmoid then 0.5-threshold ``outLabel`` —
        ``FullyConnectedNetwork.cc:13-25``); "softmax" uses the standard
        inference tail."""
        if out_mode not in ("softmax", "label"):
            raise ValueError(
                f"out_mode must be 'softmax' or 'label', got {out_mode!r}")
        cd = self.compute_dtype

        def whole_network(x: BlockedTensor) -> BlockedTensor:
            h = nn_ops.bias_relu(matmul_t(params.w1, x, cd, accum_dtype=cd),
                                 params.b1)
            yo = matmul(params.wo, h, cd)
            if out_mode == "label":
                p = nn_ops.bias_sigmoid(yo, params.bo)
                # padding margins are sigmoid-remasked to 0 → stay 0
                return p.with_data((p.data > 0.5).astype(p.data.dtype))
            return nn_ops.ff_output_layer(yo, params.bo, axis=0)

        net = Apply(ScanSet(self.db, "inputs"), whole_network,
                    label="FullyConnectedNetwork")
        return WriteSet(net, self.db, "output")

    def inference_fused(self, client: Client,
                        out_mode: str = "softmax") -> BlockedTensor:
        """FF_proj-style single-UDF inference over stored weights."""
        sink = self.build_fused_inference_dag(self.params_from_store(client),
                                              out_mode)
        results = client.execute_computations(
            sink, job_name=f"{self.db}-inference-fused-{out_mode}")
        return next(iter(results.values()))

    # --- pure-function forms (for jit/bench/sharding) -----------------
    def params_from_store(self, client: Client) -> FFParams:
        return FFParams(
            w1=client.get_tensor(self.db, "w1"),
            b1=client.get_tensor(self.db, "b1"),
            wo=client.get_tensor(self.db, "wo"),
            bo=client.get_tensor(self.db, "bo"),
        )

    def forward(self, params: FFParams, inputs: BlockedTensor) -> BlockedTensor:
        """(batch x features) → softmax probs (labels x batch). Same math
        as the DAG, one traced function. When reduced precision is opted
        in (``compute_dtype``) the hidden activation also stays in that
        dtype (accum_dtype), halving its HBM traffic; the output layer
        always accumulates f32 for the softmax."""
        cd = self.compute_dtype
        h = nn_ops.bias_relu(matmul_t(params.w1, inputs, cd, accum_dtype=cd),
                             params.b1)
        yo = matmul(params.wo, h, cd)
        return nn_ops.ff_output_layer(yo, params.bo, axis=0)

    def logits(self, params: FFParams, inputs: BlockedTensor) -> BlockedTensor:
        cd = self.compute_dtype
        h = nn_ops.bias_relu(matmul_t(params.w1, inputs, cd, accum_dtype=cd),
                             params.b1)
        return matmul(params.wo, h, cd)

    # --- training (TPU-first extension; powers dryrun_multichip) ------
    def loss(self, params: FFParams, inputs: BlockedTensor,
             labels_onehot: BlockedTensor) -> jax.Array:
        """Masked softmax cross-entropy. ``labels_onehot``: (labels x batch)
        blocked like the output."""
        lg = self.logits(params, inputs)
        logits_masked = jnp.where(lg.mask(jnp.bool_), lg.data, -jnp.inf)
        logp = jax.nn.log_softmax(logits_masked, axis=0)
        logp = jnp.nan_to_num(logp, nan=0.0, neginf=0.0)
        batch = inputs.shape[0]
        return -jnp.sum(labels_onehot.data * logp) / batch

    def train_step(self, params: FFParams, inputs: BlockedTensor,
                   labels_onehot: BlockedTensor,
                   lr: float = 0.1) -> Tuple[FFParams, jax.Array]:
        loss, grads = jax.value_and_grad(self.loss)(params, inputs, labels_onehot)
        new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new, loss
