"""Logistic regression in the database.

Mirrors the reference LogReg workload (``src/LogReg/headers/
Logistic_Regression.h``; driver ``src/tests/source/
LogisticRegressionTest.cc``), which reuses the FF operator family:
one ``FFTransposeMult`` + ``FFAggMatrix`` matmul followed by
``FFTransposeBiasSumSigmoid`` (``src/FF/source/SimpleFF.cc:428-499``).
Adds a training step (logistic loss + SGD) for the TPU-first story.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops import nn as nn_ops
from netsdb_tpu.ops.matmul import matmul_t
from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LogRegParams:
    w: BlockedTensor  # (1 x features) — a single-row blocked matrix
    b: BlockedTensor  # (1 x 1)


class LogRegModel:
    SETS = ("inputs", "w", "b", "output")

    def __init__(self, db: str = "logreg", block: Tuple[int, int] = (512, 512),
                 compute_dtype: Optional[str] = None):
        self.db = db
        self.block = block
        self.compute_dtype = compute_dtype

    def setup(self, client: Client, placements=None) -> None:
        """``placements``: set name → Placement; ``inputs`` column-
        (batch-)sharded on ``data`` distributes the whole inference DAG
        (weights are a single row — replicate them)."""
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s))

    def load_weights(self, client: Client, w: np.ndarray, b: float) -> None:
        client.send_matrix(self.db, "w", np.asarray(w).reshape(1, -1),
                           (1, self.block[1]))
        client.send_matrix(self.db, "b", np.asarray([[b]], dtype=np.float32),
                           (1, 1))

    def load_inputs(self, client: Client, x: np.ndarray) -> None:
        client.send_matrix(self.db, "inputs", x, self.block)

    def build_inference_dag(self) -> WriteSet:
        cd = self.compute_dtype
        w = ScanSet(self.db, "w")
        x = ScanSet(self.db, "inputs")
        b = ScanSet(self.db, "b")
        z = Join(w, x, fn=lambda ww, xx: matmul_t(ww, xx, cd),
                 label="FFTransposeMult")
        out = Join(z, b, fn=lambda zz, bb: nn_ops.bias_sigmoid(zz, bb),
                   label="FFTransposeBiasSumSigmoid")
        return WriteSet(out, self.db, "output")

    def inference(self, client: Client) -> BlockedTensor:
        """probabilities (1 x batch)."""
        res = client.execute_computations(self.build_inference_dag(),
                                          job_name=f"{self.db}-inference")
        return next(iter(res.values()))

    # --- pure forms ---------------------------------------------------
    def params_from_store(self, client: Client) -> LogRegParams:
        return LogRegParams(w=client.get_tensor(self.db, "w"),
                            b=client.get_tensor(self.db, "b"))

    def forward(self, params: LogRegParams, x: BlockedTensor) -> BlockedTensor:
        z = matmul_t(params.w, x, self.compute_dtype)
        return nn_ops.bias_sigmoid(z, params.b)

    def loss(self, params: LogRegParams, x: BlockedTensor,
             y: jax.Array) -> jax.Array:
        """Binary cross-entropy; ``y``: (batch,) in {0,1}."""
        z = matmul_t(params.w, x, self.compute_dtype)
        logits = z.to_dense().reshape(-1) + params.b.data[0, 0]
        return jnp.mean(
            jnp.maximum(logits, 0) - logits * y
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )

    def train_step(self, params: LogRegParams, x: BlockedTensor, y: jax.Array,
                   lr: float = 0.5):
        l, g = jax.value_and_grad(self.loss)(params, x, y)
        return jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g), l
