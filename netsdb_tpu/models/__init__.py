"""Model workloads — the reference's in-database ML applications
(reference layer 16: ``src/FF``, ``src/LogReg``, ``src/word2vec``,
``src/conv2d_proj``, ``src/conv2d_memory_fusion``, ``src/LSTM``)."""

from netsdb_tpu.models.conv2d import Conv2DModel
from netsdb_tpu.models.decode import DecodeRuntime, deploy_decode_model
from netsdb_tpu.models.ff import FFModel
from netsdb_tpu.models.logreg import LogRegModel
from netsdb_tpu.models.lstm_model import LSTMModel
from netsdb_tpu.models.serving import ModelServing, ff_serving
from netsdb_tpu.models.text_classifier import TextClassifierModel
from netsdb_tpu.models.transformer import TransformerLayerModel
from netsdb_tpu.models.word2vec import Word2VecModel

__all__ = [
    "Conv2DModel", "DecodeRuntime", "FFModel", "LogRegModel",
    "LSTMModel", "ModelServing", "TextClassifierModel",
    "TransformerLayerModel", "Word2VecModel", "deploy_decode_model",
    "ff_serving",
]
