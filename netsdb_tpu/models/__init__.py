"""Model workloads — the reference's in-database ML applications
(reference layer 16: ``src/FF``, ``src/LogReg``, ``src/word2vec``,
``src/conv2d_proj``, ``src/conv2d_memory_fusion``, ``src/LSTM``)."""

from netsdb_tpu.models.ff import FFModel

__all__ = ["FFModel"]
