"""Transformer layer serving — the long-context flagship.

No reference analogue exists (netsDB predates attention, SURVEY §5);
this model completes the framework's long-context story: a transformer
block whose weights live in database sets like every other model's, a
single-chip forward, and a sequence-parallel forward where activations
are sharded on the sequence axis and attention runs as ring attention
over the mesh (``netsdb_tpu.parallel.ring``) — the capability that
subsumes the reference's "scale the big dimension" relational SUMMA.

Layer = pre-LN MHA + residual, pre-LN MLP (gelu) + residual.
x: (batch, seq, embed).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from netsdb_tpu.client import Client
from netsdb_tpu.ops.attention import mha_forward
from netsdb_tpu.parallel.ring import ring_attention

_HI = jax.lax.Precision.HIGHEST


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TransformerLayerParams:
    w_qkv: jax.Array   # (E, 3E)
    w_out: jax.Array   # (E, E)
    w_up: jax.Array    # (E, 4E)
    w_down: jax.Array  # (4E, E)


class TransformerLayerModel:
    SETS = ("w_qkv", "w_out", "w_up", "w_down")

    def __init__(self, db: str = "transformer", num_heads: int = 8):
        self.db = db
        self.num_heads = num_heads

    def setup(self, client: Client) -> None:
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s)

    def load_random_weights(self, client: Client, embed: int,
                            seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        scale = embed ** -0.5
        for name, shape in (("w_qkv", (embed, 3 * embed)),
                            ("w_out", (embed, embed)),
                            ("w_up", (embed, 4 * embed)),
                            ("w_down", (4 * embed, embed))):
            client.send_matrix(self.db, name,
                               rng.standard_normal(shape).astype(np.float32)
                               * scale, (min(512, shape[0]), min(512, shape[1])))

    def params_from_store(self, client: Client) -> TransformerLayerParams:
        g = lambda n: client.get_tensor(self.db, n).to_dense()
        return TransformerLayerParams(w_qkv=g("w_qkv"), w_out=g("w_out"),
                                      w_up=g("w_up"), w_down=g("w_down"))

    # --- math ---------------------------------------------------------
    @staticmethod
    def _ln(x):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5)

    def _mlp(self, x, p: TransformerLayerParams):
        h = jax.nn.gelu(jnp.einsum("bse,ef->bsf", x, p.w_up, precision=_HI))
        return jnp.einsum("bsf,fe->bse", h, p.w_down, precision=_HI)

    def forward(self, p: TransformerLayerParams, x: jax.Array,
                causal: bool = True) -> jax.Array:
        """Single-chip forward."""
        a = mha_forward(self._ln(x), p.w_qkv, p.w_out, self.num_heads,
                        causal=causal)
        x = x + a
        return x + self._mlp(self._ln(x), p)

    def forward_sp(self, p: TransformerLayerParams, x: jax.Array, mesh: Mesh,
                   axis: str = "data", causal: bool = True) -> jax.Array:
        """Sequence-parallel forward: x sharded (None, axis, None). The
        projections/MLP are per-position (XLA keeps them local); the
        attention core rotates k/v around the ring."""
        from netsdb_tpu.ops.attention import merge_project, qkv_project

        q, k, v = qkv_project(self._ln(x), p.w_qkv, self.num_heads)
        spec = NamedSharding(mesh, P(None, None, axis, None))
        q, k, v = (jax.lax.with_sharding_constraint(t, spec)
                   for t in (q, k, v))
        out = ring_attention(q, k, v, mesh, axis=axis, causal=causal)
        x = x + merge_project(out, p.w_out)
        return x + self._mlp(self._ln(x), p)

    def loss(self, p: TransformerLayerParams, x: jax.Array,
             targets: jax.Array) -> jax.Array:
        """Simple next-step regression loss for the training dry-run."""
        out = self.forward(p, x)
        return jnp.mean((out - targets) ** 2)

    def train_step(self, p, x, targets, lr: float = 1e-2):
        l, g = jax.value_and_grad(self.loss)(p, x, targets)
        return jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g), l
