"""Transformer layer serving — the long-context flagship.

No reference analogue exists (netsDB predates attention, SURVEY §5);
this model completes the framework's long-context story: a transformer
block whose weights live in database sets like every other model's, a
single-chip forward, and a sequence-parallel forward where activations
are sharded on the sequence axis and attention runs as ring attention
over the mesh (``netsdb_tpu.parallel.ring``) — the capability that
subsumes the reference's "scale the big dimension" relational SUMMA.

Layer = pre-LN MHA + residual, pre-LN MLP (gelu) + residual.
x: (batch, seq, embed).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from netsdb_tpu.client import Client
from netsdb_tpu.ops.attention import mha_forward
from netsdb_tpu.parallel.ring import ring_attention

_HI = jax.lax.Precision.HIGHEST


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TransformerLayerParams:
    w_qkv: jax.Array   # (E, 3E)
    w_out: jax.Array   # (E, E)
    w_up: jax.Array    # (E, 4E)
    w_down: jax.Array  # (4E, E)


class TransformerLayerModel:
    SETS = ("w_qkv", "w_out", "w_up", "w_down")

    def __init__(self, db: str = "transformer", num_heads: int = 8):
        self.db = db
        self.num_heads = num_heads

    def setup(self, client: Client, placements=None,
              storages=None) -> None:
        """``placements`` maps set name → Placement (weights typically
        replicated; the activation set sharded on the sequence axis) —
        the long-context model declared distributed the same way the
        relational sets are (round 3). ``storages`` maps set name →
        "memory"|"paged": paged weight sets stream through the staged
        DAG (``build_forward_dag_staged``)."""
        client.create_database(self.db)
        for s in self.SETS:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s),
                              storage=(storages or {}).get(s, "memory"))

    def load_random_weights(self, client: Client, embed: int,
                            seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        scale = embed ** -0.5
        for name, shape in (("w_qkv", (embed, 3 * embed)),
                            ("w_out", (embed, embed)),
                            ("w_up", (embed, 4 * embed)),
                            ("w_down", (4 * embed, embed))):
            client.send_matrix(self.db, name,
                               rng.standard_normal(shape).astype(np.float32)
                               * scale, (min(512, shape[0]), min(512, shape[1])))

    def params_from_store(self, client: Client) -> TransformerLayerParams:
        g = lambda n: client.get_tensor(self.db, n).to_dense()
        return TransformerLayerParams(w_qkv=g("w_qkv"), w_out=g("w_out"),
                                      w_up=g("w_up"), w_down=g("w_down"))

    # --- math ---------------------------------------------------------
    @staticmethod
    def _ln(x):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5)

    def _mlp(self, x, p: TransformerLayerParams):
        h = jax.nn.gelu(jnp.einsum("bse,ef->bsf", x, p.w_up, precision=_HI))
        return jnp.einsum("bsf,fe->bse", h, p.w_down, precision=_HI)

    def forward(self, p: TransformerLayerParams, x: jax.Array,
                causal: bool = True) -> jax.Array:
        """Single-chip forward."""
        a = mha_forward(self._ln(x), p.w_qkv, p.w_out, self.num_heads,
                        causal=causal)
        x = x + a
        return x + self._mlp(self._ln(x), p)

    def forward_sp(self, p: TransformerLayerParams, x: jax.Array, mesh: Mesh,
                   axis: str = "data", causal: bool = True) -> jax.Array:
        """Sequence-parallel forward: x sharded (None, axis, None). The
        projections/MLP are per-position (XLA keeps them local); the
        attention core rotates k/v around the ring."""
        from netsdb_tpu.ops.attention import merge_project, qkv_project

        q, k, v = qkv_project(self._ln(x), p.w_qkv, self.num_heads)
        spec = NamedSharding(mesh, P(None, None, axis, None))
        q, k, v = (jax.lax.with_sharding_constraint(t, spec)
                   for t in (q, k, v))
        out = ring_attention(q, k, v, mesh, axis=axis, causal=causal)
        x = x + merge_project(out, p.w_out)
        return x + self._mlp(self._ln(x), p)

    # --- set-API serving (round 3) ------------------------------------
    def load_inputs(self, client: Client, x: np.ndarray,
                    input_set: str = "x", placement=None) -> None:
        """Store an activation batch (batch, seq, embed) as a raw-array
        set through the public data path (works with the in-process
        client AND the RemoteClient — and therefore fans out to
        follower daemons in multi-host mode). With a placement whose
        spec shards dim 1 (the sequence axis), ingest shards the
        sequence over the mesh — long-context inputs live distributed
        in the database like any other set. Unplaced inputs get a
        trivial replicated placement so the stored item is a device
        array either way (the executor's traced-scan path takes
        jax.Arrays; bare numpy items stay host objects by design)."""
        from netsdb_tpu.parallel.placement import Placement

        if placement is None:
            placement = Placement((("data", 1),),
                                  (None,) * np.asarray(x).ndim)
        client.create_set(self.db, input_set, placement=placement)
        client.clear_set(self.db, input_set)
        client.send_data(self.db, input_set,
                         [np.asarray(x, np.float32)])

    def build_forward_dag(self, client: Client, input_set: str = "x",
                          output_set: str = "y", causal: bool = True,
                          placement=None):
        """SCAN(x) ⋈ SCAN(weights...) → forward → OUTPUT. When the
        input set's placement shards the sequence axis, the traced body
        runs the ring-attention sequence-parallel forward over that
        placement's mesh; unplaced sets run the single-chip forward —
        the SAME DAG, distribution decided by how the sets were created
        (netsdb_tpu round-3 rule).

        ``placement``: the input set's placement. Defaults to looking
        it up in the client's store; a RemoteClient has no store, so
        remote callers pass the placement they created the set with."""
        from netsdb_tpu.plan.computations import Join, ScanSet, WriteSet
        from netsdb_tpu.storage.store import SetIdentifier

        if placement is None and hasattr(client, "store"):
            placement = client.store.placement_of(
                SetIdentifier(self.db, input_set))
        mesh = axis = None
        if placement is not None:
            sharded_axes = [a for a in placement.spec if a is not None]
            if sharded_axes:
                mesh = placement.mesh()
                ax = sharded_axes[0]
                axis = ax[0] if isinstance(ax, tuple) else ax
                if mesh.shape[axis] == 1:
                    mesh = axis = None  # degraded single-device mesh

        def fwd(gathered, w_down_bt):
            x, wq, wo, wu = gathered
            p = TransformerLayerParams(
                w_qkv=wq.to_dense(), w_out=wo.to_dense(),
                w_up=wu.to_dense(), w_down=w_down_bt.to_dense())
            if mesh is not None:
                return self.forward_sp(p, x, mesh, axis, causal=causal)
            return self.forward(p, x, causal=causal)

        g1 = Join(ScanSet(self.db, input_set), ScanSet(self.db, "w_qkv"),
                  fn=lambda a, b: (a, b), label="gather:w_qkv",
                  passthrough=True)
        g2 = Join(g1, ScanSet(self.db, "w_out"),
                  fn=lambda a, b: a + (b,), label="gather:w_out",
                  passthrough=True)
        g3 = Join(g2, ScanSet(self.db, "w_up"),
                  fn=lambda a, b: a + (b,), label="gather:w_up",
                  passthrough=True)
        # the traced body CLOSES OVER the mesh, so the compiled-plan
        # cache key (built from labels) must pin the mesh identity —
        # axis names, shape AND device ids — or a same-shaped DAG built
        # for a different/reinitialized mesh would reuse a stale closure
        mesh_tag = (None if mesh is None else
                    (tuple(mesh.shape.items()),
                     tuple(d.id for d in mesh.devices.flat)))
        out = Join(g3, ScanSet(self.db, "w_down"), fn=fwd,
                   label=f"transformer-fwd:{self.num_heads}:{causal}:"
                         f"{axis}:{mesh_tag}")
        return WriteSet(out, self.db, output_set)

    def build_forward_dag_staged(self, input_set: str = "x",
                                 output_set: str = "y",
                                 causal: bool = True):
        """Forward as STAGED Computation nodes (ln → qkv-proj →
        attention core → out-proj → residual → ln → MLP-up → MLP-down
        → residual) instead of one fused fn, so EVERY weight matrix
        (w_qkv, w_out, w_up, w_down) may live in a ``storage="paged"``
        set and STREAM through the DAG: each weight's row blocks are
        contraction slices accumulated by a reduce-mode
        :class:`~netsdb_tpu.plan.fold.TensorFold` (the reference's
        page-fed weight scans, ``SimpleFF.cc:94-290``, applied to the
        transformer layer). With resident sets the same DAG evaluates
        the plain fns — storage stays a property of the set, not the
        query."""
        from netsdb_tpu.plan.computations import (Apply, Join, ScanSet,
                                                  WriteSet)
        from netsdb_tpu.plan.fold import TensorFold

        heads, db = self.num_heads, self.db

        def contract_partial(eq):
            def partial(carry, start, block, acts):
                sl = jax.lax.dynamic_slice_in_dim(
                    acts, start, block.shape[0], axis=-1)
                p = jnp.einsum(eq, sl, block, precision=_HI)
                return p if carry is None else carry + p
            return partial

        def proj_fold():  # (B,S,E') @ paged (E',F): rows = contraction
            return TensorFold(mode="reduce",
                              partial=contract_partial("bse,ef->bsf"))

        from netsdb_tpu.ops.attention import (attention_dispatch,
                                              merge_heads,
                                              split_qkv_heads)

        ln1 = Apply(ScanSet(db, input_set), fn=self._ln, label="ln1")
        # qkv projection: w_qkv (E,3E) may be paged — its row blocks
        # are contraction slices of ln(x)
        qkv = Join(ln1, ScanSet(db, "w_qkv"),
                   fn=lambda xs, w: jnp.einsum("bse,ef->bsf", xs,
                                               w.to_dense(),
                                               precision=_HI),
                   tensor_fold=proj_fold(), label="qkv-proj")

        def attn_core(q_k_v):
            q, k, v = split_qkv_heads(q_k_v, heads)
            return merge_heads(attention_dispatch(q, k, v,
                                                  causal=causal))

        core = Apply(qkv, fn=attn_core,
                     label=f"attn-core:{heads}:{causal}")
        # out projection: w_out (E,E) may be paged the same way
        proj = Join(core, ScanSet(db, "w_out"),
                    fn=lambda os, w: jnp.einsum("bse,ef->bsf", os,
                                                w.to_dense(),
                                                precision=_HI),
                    tensor_fold=proj_fold(), label="out-proj")
        a1 = Join(ScanSet(db, input_set), proj,
                  fn=lambda x, a: x + a, label="residual1")
        ln2 = Apply(a1, fn=self._ln, label="ln2")

        h = Join(ln2, ScanSet(db, "w_up"),
                 fn=lambda xs, wu: jax.nn.gelu(jnp.einsum(
                     "bse,ef->bsf", xs, wu.to_dense(), precision=_HI)),
                 tensor_fold=TensorFold(
                     mode="reduce", partial=contract_partial("bse,ef->bsf"),
                     finalize=lambda c, xs: jax.nn.gelu(c)),
                 label="mlp-up")
        mlp = Join(h, ScanSet(db, "w_down"),
                   fn=lambda hs, wd: jnp.einsum(
                       "bsf,fe->bse", hs, wd.to_dense(), precision=_HI),
                   tensor_fold=TensorFold(
                       mode="reduce",
                       partial=contract_partial("bsf,fe->bse")),
                   label="mlp-down")
        out = Join(a1, mlp, fn=lambda a, m2: a + m2, label="residual2")
        return WriteSet(out, db, output_set)

    def serve_forward(self, client: Client, input_set: str = "x",
                      output_set: str = "y", causal: bool = True,
                      placement=None) -> jax.Array:
        sink = self.build_forward_dag(client, input_set, output_set,
                                      causal, placement=placement)
        results = client.execute_computations(
            sink, job_name=f"{self.db}-forward")
        return next(iter(results.values()))

    def loss(self, p: TransformerLayerParams, x: jax.Array,
             targets: jax.Array) -> jax.Array:
        """Simple next-step regression loss for the training dry-run."""
        out = self.forward(p, x)
        return jnp.mean((out - targets) ** 2)

    def train_step(self, p, x, targets, lr: float = 1e-2):
        l, g = jax.value_and_grad(self.loss)(p, x, targets)
        return jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g), l
