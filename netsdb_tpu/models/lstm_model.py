"""LSTM cell serving in the database.

Mirrors the reference LSTM workload (``src/tests/source/LSTMTest.cc``,
559 LoC): twelve weight sets (w/u per gate + biases), input and state
sets, one cell step as a computation DAG of 8 matmuls +
``LSTMThreeWaySum``/``LSTMHiddenState`` fusions. The reference driver
re-issues the DAG per timestep; here a sequence runs under one
``lax.scan`` (``ops.lstm.lstm_unroll``) so XLA compiles the whole
recurrence once.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.core.blocked import BlockedTensor
from netsdb_tpu.ops.lstm import LSTMParams, lstm_cell, lstm_unroll

_GATES = ("i", "f", "c", "o")


class LSTMModel:
    def __init__(self, db: str = "lstm", block: Tuple[int, int] = (512, 512),
                 compute_dtype: Optional[str] = None):
        self.db = db
        self.block = block
        self.compute_dtype = compute_dtype

    @property
    def weight_sets(self):
        return ([f"w_{g}" for g in _GATES] + [f"u_{g}" for g in _GATES]
                + [f"b_{g}" for g in _GATES])

    def setup(self, client: Client, placements=None) -> None:
        """``placements``: set name → Placement (createSet-time
        PartitionPolicy). Typical mesh layout: gate weights ``w_*``
        row-sharded on ``model``, state ``h``/``c`` batch-sharded on
        ``data``, biases replicated — the stored shardings make
        ``step``/``run_sequence`` distribute through XLA with no code
        change."""
        client.create_database(self.db)
        for s in self.weight_sets + ["x", "h", "c", "h_out", "c_out"]:
            client.create_set(self.db, s,
                              placement=(placements or {}).get(s))

    def load_weights(self, client: Client, weights: dict) -> None:
        """``weights``: {'w_i': (hidden x input), ..., 'b_i': (hidden,)}."""
        for g in _GATES:
            client.send_matrix(self.db, f"w_{g}", weights[f"w_{g}"], self.block)
            client.send_matrix(self.db, f"u_{g}", weights[f"u_{g}"], self.block)
            b = np.asarray(weights[f"b_{g}"]).reshape(-1, 1)
            client.send_matrix(self.db, f"b_{g}", b, (self.block[0], 1))

    def load_state(self, client: Client, h: np.ndarray, c: np.ndarray) -> None:
        client.send_matrix(self.db, "h", h, self.block)
        client.send_matrix(self.db, "c", c, self.block)

    def params_from_store(self, client: Client) -> LSTMParams:
        g = lambda name: client.get_tensor(self.db, name)
        return LSTMParams(
            w_i=g("w_i"), w_f=g("w_f"), w_c=g("w_c"), w_o=g("w_o"),
            u_i=g("u_i"), u_f=g("u_f"), u_c=g("u_c"), u_o=g("u_o"),
            b_i=g("b_i"), b_f=g("b_f"), b_c=g("b_c"), b_o=g("b_o"),
        )

    def step(self, client: Client, x: np.ndarray) -> Tuple[BlockedTensor, BlockedTensor]:
        """One cell step from stored state; writes h_out/c_out sets (the
        LSTMTest driver's per-step executeComputations)."""
        params = self.params_from_store(client)
        xb = BlockedTensor.from_dense(np.asarray(x, np.float32), self.block)
        h = client.get_tensor(self.db, "h")
        c = client.get_tensor(self.db, "c")
        h2, c2 = lstm_cell(params, xb, h, c, self.compute_dtype)
        from netsdb_tpu.storage.store import SetIdentifier

        client.store.put_tensor(SetIdentifier(self.db, "h_out"), h2)
        client.store.put_tensor(SetIdentifier(self.db, "c_out"), c2)
        return h2, c2

    def run_sequence(self, client: Client, xs: np.ndarray):
        """``xs``: (T, input, batch) → (h_T, c_T, all h). One lax.scan."""
        params = self.params_from_store(client)
        h = client.get_tensor(self.db, "h")
        c = client.get_tensor(self.db, "c")
        T = xs.shape[0]
        # x's row blocking must match w's COLUMN blocking (x rows are the
        # contraction dim of w·x), and its column blocking h's
        x_block = (self.block[1], self.block[1])
        xs_padded = jnp.stack([
            BlockedTensor.from_dense(np.asarray(xs[t], np.float32),
                                     x_block).data
            for t in range(T)
        ])
        return lstm_unroll(params, xs_padded, h, c, self.compute_dtype)
