"""Framework configuration.

TPU-native analogue of the reference ``Configuration`` object
(``src/conf/headers/Configuration.h:22-71``): where netsDB sizes 64 MB
shared-memory pages, shuffle page sizes and thread counts, we size tensor
blocks (the sharding granularity), host page-store pages, and the device
mesh. Unlike the reference's argv-populated singleton, this is a plain
dataclass passed explicitly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple


@dataclasses.dataclass
class Configuration:
    """Global knobs; defaults chosen for TPU v5e.

    ``default_block_shape`` plays the role of netsDB's matrix block dims
    (reference tests default to 100x100 or 1000x1000 blocks,
    ``src/tests/source/FFTest.cc``); 512 is MXU/tiling friendly
    (multiple of 128 lanes / 8 sublanes).

    ``page_size_bytes`` mirrors ``Configuration::getPageSize`` (64 MB
    default) for the host-side page store.
    """

    # --- tensor blocking ---
    default_block_shape: Tuple[int, int] = (512, 512)
    # --- dtypes: MXU prefers bfloat16 inputs, f32 accumulation ---
    compute_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    storage_dtype: str = "float32"
    # --- host page store (native runtime) ---
    page_size_bytes: int = 64 * 1024 * 1024
    shared_mem_bytes: int = 4 * 1024 * 1024 * 1024
    # --- directories (reference: Configuration rootDir/catalog dirs) ---
    root_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get("NETSDB_TPU_HOME", "/tmp/netsdb_tpu")
    )
    # --- mesh defaults (data x model), overridden by parallel.mesh helpers ---
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axis_names: Tuple[str, ...] = ("data", "model")
    # --- execution ---
    num_threads: int = 4  # host-side IO/pipeline threads (not device parallelism)
    enable_compression: bool = True  # host spill compression (ref -DENABLE_COMPRESSION)
    log_level: str = "WARNING"

    @property
    def catalog_path(self) -> str:
        return os.path.join(self.root_dir, "catalog.sqlite")

    @property
    def data_dir(self) -> str:
        return os.path.join(self.root_dir, "data")

    def ensure_dirs(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)


DEFAULT_CONFIG = Configuration()
