"""Framework configuration.

TPU-native analogue of the reference ``Configuration`` object
(``src/conf/headers/Configuration.h:22-71``): where netsDB sizes 64 MB
shared-memory pages, shuffle page sizes and thread counts, we size tensor
blocks (the sharding granularity), host page-store pages, and the device
mesh. Unlike the reference's argv-populated singleton, this is a plain
dataclass passed explicitly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple


@dataclasses.dataclass
class Configuration:
    """Global knobs; defaults chosen for TPU v5e.

    ``default_block_shape`` plays the role of netsDB's matrix block dims
    (reference tests default to 100x100 or 1000x1000 blocks,
    ``src/tests/source/FFTest.cc``); 512 is MXU/tiling friendly
    (multiple of 128 lanes / 8 sublanes).

    ``page_size_bytes`` mirrors ``Configuration::getPageSize`` (64 MB
    default) for the host-side page store.
    """

    # --- tensor blocking ---
    default_block_shape: Tuple[int, int] = (512, 512)
    # --- dtypes: MXU prefers bfloat16 inputs, f32 accumulation ---
    compute_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    storage_dtype: str = "float32"
    # --- host page store (native runtime) ---
    page_size_bytes: int = 64 * 1024 * 1024
    shared_mem_bytes: int = 4 * 1024 * 1024 * 1024
    # arena cap for PAGED sets (create_set(storage="paged")); None =
    # shared_mem_bytes. Separate knob because tests cap the page pool
    # tightly (forcing spills) while host sets stay uncapped.
    page_pool_bytes: Optional[int] = None
    # --- directories (reference: Configuration rootDir/catalog dirs) ---
    root_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get("NETSDB_TPU_HOME", "/tmp/netsdb_tpu")
    )
    # --- mesh defaults (data x model), overridden by parallel.mesh helpers ---
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axis_names: Tuple[str, ...] = ("data", "model")
    # --- out-of-core staging pipeline (plan/staging.py) ---
    # host page read-ahead depth for every block/chunk stream (the
    # PageCircularBuffer between the arena reader and the consumer);
    # 0 = synchronous reads. Replaces the executor's old hardwired
    # prefetch=0 call sites.
    stream_prefetch_pages: int = 2
    # device staging double-buffer depth: how many blocks ahead the
    # background thread runs jax.device_put (with the set's sharding)
    # of the consumer's fold step; 0 = synchronous device_put (the
    # baseline path `micro-bench --staging` compares against).
    stage_depth: int = 2
    # pad streamed row chunks up to the fixed bucket ladder
    # (plan/staging.bucket_rows: powers of two and 1.5x powers of two)
    # so ragged tails / differing ingest sizes reuse one compiled step
    # per bucket instead of compiling per distinct shape. Padded rows
    # ride the validity mask; False restores exact-shape padding.
    shape_bucketing: bool = True
    # buckets per octave in the shape ladder: 2 (default — {2^k,
    # 3*2^(k-1)}, <50% pad worst case) or 4 (2^(k-1)*{1.25,1.5,1.75}
    # rungs added — <25% pad at twice the compiles per octave).
    # `micro_bench --bucket-sweep` reports pad-waste vs trace-count
    # per density (the ROADMAP ladder-tuning item).
    bucket_density: int = 2
    # --- fusion-aware plan compilation (plan/fusion.py) ---
    # master switch for the region mapper: on, the streamed executor
    # compiles maximal traceable resident subgraphs as ONE XLA program
    # per region (replacing per-node jit entries) and fuses streamed
    # folds' rowwise pre-chains / traceable epilogues into the fold's
    # compiled loop. Off byte-for-byte restores the per-node paths
    # (same jit-cache keys, trace counts and EXPLAIN shape) — the safe
    # rollback the acceptance gate pins.
    plan_fusion: bool = True
    # smallest node count worth compiling as one spine region (a
    # 1-node "region" is exactly today's per-node jit; floor 2)
    fusion_min_region: int = 2
    # cost feed for fusion decisions: "ledger" reads the per-(job,
    # node-label) OperatorLedger means (wall vs device gap = dispatch
    # overhead, retrace rates veto churn-prone labels), falling back
    # to a static estimate for never-seen labels; "static" forces the
    # fallback everywhere (cold daemons, deterministic tests)
    fusion_cost_source: str = "ledger"
    # region partitioner: "optimal" solves each maximal fusable run
    # exactly (DP over the region lattice — the runs are
    # topo-contiguous and convex, so contiguous-segment DP IS the
    # exact solution) under the staged-bytes budget below, splitting
    # an over-budget region at its cheapest edge; "greedy" restores
    # the PR 10 flush-the-whole-run mapper byte-for-byte (same region
    # ids, fingerprints, jit keys, counters) — the rollback arm the
    # A/B advisor compares against.
    fusion_mapper: str = "optimal"
    # HBM/pin byte budget one fused region's staged inputs may occupy
    # (cost model: per-label ledger means of bytes_in/stage.bytes,
    # static per-node fallback for cold labels). A run whose single-
    # region staging estimate exceeds this SPLITS at the cheapest
    # edge (fusion.splits ticks) instead of falling back per-node.
    # 0 = unbounded (the default — budget pressure is an operator/
    # TPU-rig decision, not something a CPU container can size).
    fusion_stage_budget_bytes: int = 0
    # --- cross-query device-resident set cache (storage/devcache.py) ---
    # byte budget for placed set blocks kept DEVICE-RESIDENT across
    # queries and serve requests (the buffer-pool role: the second
    # query over a hot set performs zero host->device transfers).
    # Entries key on (db, set, version, bucket, sharding); every write
    # path bumps the set version, so the cache can never serve stale
    # blocks. 0 disables. LRU-evicted under the budget.
    device_cache_bytes: int = 256 * 1024 * 1024
    # block-granular PARTIAL-RUN caching (the netsDB pin-per-page
    # discipline): entries install per block under (scope, kind,
    # bucket, sharding, block_range) as they stream — partial
    # consumption caches the consumed prefix — and lookups STITCH
    # contiguous cached ranges into the staged stream (cached ranges
    # serve from HBM with zero arena reads, gaps fall through to the
    # host-prefetch→upload pipeline). Invalidation is per-page dirty
    # ranges (SetStore._touch): an append drops only entries
    # intersecting the appended tail, so a huge set's warm prefix
    # survives small writes. False restores the whole-run
    # version-keyed behavior byte-for-byte (same keys, counters,
    # EXPLAIN — the rollback contract pinned by test).
    device_cache_partial: bool = True
    # pinnable hot-prefix budget (bytes, partial mode only): a set's
    # HEAD blocks — the contiguous prefix from row 0, in install
    # order — are marked pinned until this global budget is spent;
    # pinned entries are skipped by LRU eviction (dirty-range
    # invalidation still drops them). 0 disables pinning.
    device_cache_pin_bytes: int = 0
    # bound on the per-set dirty-range log (SetStore._touch): beyond
    # this many un-collapsed ranges the log folds to whole-scope (a
    # pathological writer degrades to today's invalidate-everything,
    # never to unbounded memory).
    device_cache_dirty_log: int = 64
    # --- distributed linear algebra (parallel/summa.py + reshard.py) ---
    # route streamed matmuls over paged operands through the
    # SUMMA-style distributed engine when >1 device is visible: each
    # mesh participant stages ONLY its own panel of the operands
    # (1/N of the bytes per host) and one compiled round program
    # broadcasts B panels per step over the mesh axis, accumulating
    # C tiles in place (arxiv 2112.09017). Off (default) keeps the
    # single-device block stream byte-for-byte.
    distributed_matmul: bool = False
    # participants for the SUMMA mesh: None = every visible device;
    # N caps it at the first N devices (the tier-1 virtual mesh tests
    # pin 4 of the suite's 8 host-platform devices)
    summa_participants: Optional[int] = None
    # 2-d processor grid for SUMMA ("PRxPC", e.g. "2x2", or a (pr, pc)
    # pair): operands whose BOTH dims exceed one host tile over the
    # full grid — each device stages 1/(pr*pc) of A AND of B, with
    # dual masked-psum broadcasts per step (arxiv 2112.09017 §III).
    # None (default) keeps the 1-d row-dealt mesh. A grid that does
    # not fit the visible device set falls back to 1-d; cached device
    # blocks move between the layouts via parallel/reshard.py.
    summa_grid: Optional[str] = None
    # derive the hot-prefix pin budget AUTOMATICALLY from the
    # attribution ledger's hot-set table on the scheduler-feedback
    # cadence (serve/sched/feedback.pin_budget — pinned formula),
    # when device_cache_pin_bytes is unset (0). The devcache stats
    # section annotates the active budget with "pin_auto": true.
    device_cache_pin_auto: bool = False
    # donate fold-step accumulators to XLA (donate_argnums on arg 0) so
    # per-block state updates reuse the same HBM buffer. None = auto:
    # on for backends that implement donation (TPU/GPU), off for CPU.
    donate_fold_buffers: Optional[bool] = None
    # --- observability (netsdb_tpu/obs/) ---
    # master switch for query-scoped tracing: on, every serve request
    # carrying a query id records a span profile (the -DPROFILING spans,
    # structured); off, span calls take the one-check fast path and
    # GET_TRACE returns empty. Metrics counters stay live either way
    # (they are integers, not allocations).
    obs_enabled: bool = True
    # completed query profiles retained for GET_TRACE (a bounded ring —
    # a year-long daemon holds exactly this many profiles)
    obs_trace_ring: int = 64
    # per-histogram retained samples in the metrics registry (exact
    # count/total/max are kept forever; quantiles come from the last N)
    obs_hist_samples: int = 512
    # 1-in-N query-id minting (obs.sample_qid): 1 traces every query
    # (the PR 5 behavior); N>1 mints a qid — and therefore pays span
    # recording, PUT_TRACE shipping and the optional device profile —
    # for one request in N, so high-QPS serving traces at bounded cost
    obs_trace_sample: int = 1
    # queries whose trace total exceeds this many seconds persist their
    # FULL profile to the bounded on-disk slowlog ring
    # (<root>/slowlog/, obs/slowlog.py — survives restarts); 0/None
    # disables
    obs_slow_query_s: Optional[float] = 5.0
    # slowlog files retained (oldest pruned beyond this)
    obs_slowlog_entries: int = 64
    # opt-in per-query jax.profiler sessions: a traced serve request
    # captures a REAL device profile into <dir>/<qid> (one session at a
    # time; concurrent traced queries skip, never queue). None = off.
    obs_device_profile_dir: Optional[str] = None
    # per-operator plan profiling (obs/operators.py): on, every TRACED
    # query additionally records an EXPLAIN ANALYZE tree (per-node
    # wall/device time, rows, chunk + cache/compile counters) into its
    # profile and the cross-query operator ledger; off, only explicit
    # EXECUTE(explain=True) requests record. Cost rides the trace
    # sampling knob — `micro_bench --explain-overhead` pins it < 1%.
    obs_explain: bool = True
    # continuous telemetry history (obs/history.py): the daemon
    # snapshots the registry's numeric surface every
    # obs_history_interval_s seconds into a ring of obs_history_len
    # readings (bounded: ring length x snapshot size), from which
    # GET_METRICS/`cli obs --top` derive rates (QPS, staged MB/s,
    # hit-rate trends). interval <= 0 or len < 2 disables the thread.
    obs_history_interval_s: float = 5.0
    obs_history_len: int = 120
    # --- serve-side query scheduler (netsdb_tpu/serve/sched/) ---
    # lane name -> weight for the weighted-deficit admission policy
    # (serve/sched/queue.py). Lanes not listed here get weight 1.0 on
    # first use. The daemon keys lanes by the frame's LANE_KEY hint,
    # falling back to its CLIENT_ID_KEY identity — per-client lanes
    # with zero client changes. None = every lane weight 1 (pure FIFO
    # fairness with aging).
    sched_lanes: Optional[Dict[str, float]] = None
    # max requests QUEUED per lane before the typed LaneSaturated
    # rejection (distinct from AdmissionFull: "this tenant is over its
    # share", not "the daemon is drowning"); 0 = unbounded lanes
    sched_lane_quota: int = 0
    # anti-starvation aging: every N grants, the lane whose head
    # waiter has waited longest is served regardless of weights — a
    # saturated low-priority lane admits within a bounded number of
    # high-priority admissions. 0 disables aging (pure deficit).
    sched_aging_every: int = 8
    # collapse byte-identical idempotent EXECUTE frames into ONE
    # execution fanned out to all waiters (serve/sched/coalesce.py);
    # each waiter keeps its own qid/trace/idempotency attribution
    sched_coalesce: bool = True
    # completed-fingerprint retention window (serve/sched/coalesce.py):
    # a byte-identical idempotent EXECUTE arriving within this many
    # seconds AFTER its coalesce leader finished still hits — the
    # retained reply is served under the late waiter's own qid/token
    # (sched.coalesce_late_hits). Staleness is bounded by the TTL (the
    # same window a client retry of a just-completed request would
    # observe). Default 0 = OFF: retention dedupes DISTINCT back-to-
    # back identical queries, not just concurrent ones — a visible
    # freshness trade the operator opts into per deployment (thundering
    # retry herds, dashboard fan-out), not a universal default.
    sched_coalesce_done_ttl_s: float = 0.0
    # completed-fingerprint entries retained (oldest evicted beyond
    # this — replies can be large, the bound is entries not bytes)
    sched_coalesce_done_max: int = 32
    # cache-aware hot-set admission (serve/sched/policy.py): when a
    # cold hot-set installer is already streaming, sibling queries on
    # the same placed sets queue behind it and wake into the warm
    # device cache instead of racing cold streams through the arena
    sched_affinity: bool = True
    # bound on how long an affinity sibling waits for the installer
    # before proceeding cold anyway (correctness never depends on the
    # wait — it is purely a thrash-avoidance window)
    sched_affinity_wait_s: float = 30.0
    # --- sharded worker pool (serve/placement.py + serve/shard.py) ---
    # byte bound on the leader's handoff buffers: ingest routed to a
    # DEGRADED shard slot buffers at the leader (typed retryable
    # refusal beyond the bound) and drains — only those pages — when
    # the shard readmits. The shard-scoped resync's memory ceiling.
    shard_handoff_bytes: int = 256 * 1024 * 1024
    # --- live shard rebalancing (serve/rebalance.py) ---
    # master switch for the self-rebalancing placement loop: on, the
    # leader watches per-shard load on the sched-feedback cadence (the
    # attribution ledger + shard COLLECT_STATS fan-out feed the pinned
    # skew formula), and sustained imbalance — or the pool growing/
    # shrinking — emits a bounded slot-move plan executed over the
    # RESHARD sub-protocol: copy while the source keeps serving, seal,
    # drain the tail, commit one epoch bump (old-epoch frames get the
    # typed retryable PlacementStale), drop the source copy. Off
    # (default), slots stay frozen at create_set — the PR 13 behavior,
    # byte-identical.
    rebalance: bool = False
    # max-shard-heat / mean-shard-heat ratio beyond which the detector
    # counts a window as skewed (must exceed 1.0 — a ratio of 1 is
    # perfect balance and would move data forever)
    rebalance_skew_ratio: float = 2.0
    # consecutive skewed feedback windows required before the planner
    # emits moves (pool growth/shrink bypasses this — new capacity
    # absorbs load immediately, not rebalance_windows cadences later)
    rebalance_windows: int = 3
    # byte bound on one planning round's moves: the planner stops
    # adding slot moves once their estimated bytes exceed this, so a
    # rebalance campaign trickles instead of saturating the data
    # plane. 0 = unbounded rounds.
    rebalance_max_bytes_per_round: int = 64 * 1024 * 1024
    # --- multi-host HA (serve/ha.py + storage/mutlog.py) ---
    # how long a follower must see EVERY earlier succession peer
    # unreachable before promoting itself leader under a new term.
    # Also the client's worst-case election window: a NotLeader
    # rejection with no leader address backs off within this bound.
    # The chaos tests shrink it to fractions of a second; production
    # wants it comfortably above one heartbeat_timeout_s.
    ha_election_timeout_s: float = 5.0
    # durable mutation log (storage/mutlog.py) under <root_dir>/mutlog:
    # on, the leader appends every mirrored frame on the mirror path
    # (log-replay resync for readmitted followers instead of a whole-
    # store snapshot) and the degraded-slot handoff buffer spills its
    # batches + drain tombstones (buffered ingest survives a leader
    # RESTART; the placement map persists alongside). Off (default),
    # resync falls back to the PR 2 snapshot stream and the handoff
    # buffer is memory-only — the pre-HA behavior, byte-identical.
    ha_mutlog: bool = False
    # --- scheduler feedback loop (serve/sched/) ---
    # seed lane weights (and per-lane quotas, when sched_lane_quota is
    # set) from observed behavior instead of the static sched_lanes
    # table: the per-(client, set) attribution ledger supplies each
    # lane's request/chunk/staged-byte volumes, the OperatorLedger's
    # cost rows supply the seconds-per-chunk conversion, and lanes
    # whose historical cost-per-request is LIGHT earn proportionally
    # more weight (clamped 0.25x-4x; the documented formula in
    # serve/sched/feedback.py, pinned by test). Re-seeded every
    # sched_feedback_every admissions. Opt-in: static lanes stay the
    # default.
    sched_feedback: bool = False
    sched_feedback_every: int = 64
    # SLO burn-rate load shedding (serve/sched/feedback.py): when an
    # obs/slo.py objective breaches on ALL windows, the scheduler
    # temporarily halves the heaviest non-reserved lane's quota
    # (pinned formula: quota × SHED_FACTOR, floored at 1) and ticks
    # ``sched.shed_events``; the override lifts on the first breach-
    # free check. Checked on the feedback cadence
    # (sched_feedback_every admissions). Opt-in; needs a configured
    # sched_lane_quota to have any quota to halve.
    sched_slo_shed: bool = False
    # --- stateful interactive serving (serve/sessions.py) ---
    # idle TTL for an open decode session: state untouched for this
    # long is evicted from the devcache (spilling to the host arena)
    # and, past a second TTL window, dropped from the table entirely.
    # Chaos tests shrink it to fractions of a second.
    session_ttl_s: float = 600.0
    # per-session cap on resident state bytes (recurrent h/c vectors,
    # KV cache pages). SESSION_OPEN rejects a model whose per-session
    # state would exceed it — the admission guard that keeps one fat
    # session from evicting everyone else's working set. 0 = uncapped.
    session_state_bytes: int = 16 * 1024 * 1024
    # max concurrent sessions coalesced into ONE padded decode step
    # program (the batched GENERATE path). Batch sizes quantize onto
    # the bucket_rows ladder, so churn between 1..decode_batch_max
    # live sessions never retraces.
    decode_batch_max: int = 8
    # multi-model residency dedup (dedup/ package): on, model-set
    # ingest through models/decode.py fingerprints weight pages with
    # dedup.detector and identical pages across fine-tuned model sets
    # install ONCE under a shared mapping — N near-identical models
    # resident for ~1 model's bytes + deltas. Attribution still
    # charges each client its exact share (shared pages split by
    # refcount). Off (default), every model's pages install privately.
    model_dedup: bool = False
    # --- concurrency correctness (netsdb_tpu/analysis/ + utils/locks) ---
    # lockdep-style runtime lock-order witness: on, every TrackedLock/
    # named-RWLock acquisition records rank edges (held -> acquired)
    # into one bounded process graph and flags cycles — potential
    # AB/BA deadlocks that never fired. The tier-1 suite enables it via
    # conftest; production defaults off (disabled cost: one global
    # read + is-None check per acquisition; enabled cost pinned < 2%
    # by `micro_bench --lint-overhead`).
    lock_witness: bool = False
    # --- execution ---
    num_threads: int = 4  # host-side IO/pipeline threads (not device parallelism)
    enable_compression: bool = True  # host spill compression (ref -DENABLE_COMPRESSION)
    log_level: str = "WARNING"

    # --- persistent XLA compilation cache (reference: the master's
    # PreCompiledWorkload plan cache, src/queryPlanning/headers/
    # PreCompiledWorkload.h — here the cache holds compiled XLA
    # executables keyed by HLO hash, shared across processes, so a
    # fresh process reaches steady state without a cold compile) ---
    # "auto" = <root_dir>/compile_cache; None/"" disables. The env var
    # NETSDB_TPU_COMPILE_CACHE seeds this default (an explicitly passed
    # value wins over it, like every other dataclass field).
    compilation_cache_dir: Optional[str] = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "NETSDB_TPU_COMPILE_CACHE", "auto"))

    def __post_init__(self) -> None:
        if self.bucket_density not in (2, 4):
            raise ValueError(f"bucket_density must be 2 or 4, got "
                             f"{self.bucket_density!r}")
        if self.obs_trace_sample < 1:
            raise ValueError(f"obs_trace_sample must be >= 1, got "
                             f"{self.obs_trace_sample!r}")
        if self.fusion_cost_source not in ("ledger", "static"):
            raise ValueError(f"fusion_cost_source must be 'ledger' or "
                             f"'static', got "
                             f"{self.fusion_cost_source!r}")
        if self.fusion_mapper not in ("optimal", "greedy"):
            raise ValueError(f"fusion_mapper must be 'optimal' or "
                             f"'greedy', got {self.fusion_mapper!r}")
        if self.fusion_stage_budget_bytes < 0:
            raise ValueError(f"fusion_stage_budget_bytes must be >= 0, "
                             f"got {self.fusion_stage_budget_bytes!r}")
        if self.rebalance_skew_ratio <= 1.0:
            raise ValueError(f"rebalance_skew_ratio must be > 1.0, got "
                             f"{self.rebalance_skew_ratio!r}")
        if self.rebalance_windows < 1:
            raise ValueError(f"rebalance_windows must be >= 1, got "
                             f"{self.rebalance_windows!r}")
        if self.rebalance_max_bytes_per_round < 0:
            raise ValueError(f"rebalance_max_bytes_per_round must be "
                             f">= 0, got "
                             f"{self.rebalance_max_bytes_per_round!r}")
        if self.session_ttl_s <= 0:
            raise ValueError(f"session_ttl_s must be > 0, got "
                             f"{self.session_ttl_s!r}")
        if self.session_state_bytes < 0:
            raise ValueError(f"session_state_bytes must be >= 0, got "
                             f"{self.session_state_bytes!r}")
        if self.decode_batch_max < 1:
            raise ValueError(f"decode_batch_max must be >= 1, got "
                             f"{self.decode_batch_max!r}")

    @property
    def catalog_path(self) -> str:
        return os.path.join(self.root_dir, "catalog.sqlite")

    @property
    def data_dir(self) -> str:
        return os.path.join(self.root_dir, "data")

    def ensure_dirs(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)


_cache_path: Optional[str] = None


def enable_compilation_cache(config: "Configuration" = None) -> Optional[str]:
    """Point jax at the persistent compilation cache. Re-entrant: a
    later call with a DIFFERENT resolved directory (e.g. a Client built
    with an explicit root after the CLI enabled the default) re-points
    jax's global cache there; ``compilation_cache_dir=None`` disables.
    Returns the active directory or None."""
    global _cache_path
    cfg = config or DEFAULT_CONFIG
    path = cfg.compilation_cache_dir
    if path == "auto":
        path = os.path.join(cfg.root_dir, "compile_cache")
    if path == _cache_path:
        return path
    import jax

    if not path:
        jax.config.update("jax_compilation_cache_dir", None)
        _cache_path = None
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache everything: the queries this framework compiles are
    # worth persisting even when individually quick to build
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _cache_path = path
    return path


DEFAULT_CONFIG = Configuration()
