"""Build the native runtime (g++ → shared library), cached by mtime.

Replaces the reference's SCons build of the storage engine
(``SConstruct``); one translation unit keeps it dependency-free.
"""

from __future__ import annotations

import os
import subprocess
import threading

_lock = threading.Lock()

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_OUT_DIR = os.path.join(_NATIVE_DIR, "build")


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str = "pagestore", force: bool = False) -> str:
    """Compile ``native/<name>.cpp`` if missing or stale; returns the
    .so path. One translation unit per library keeps it
    dependency-free."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    out = os.path.join(_OUT_DIR, f"lib{name}.so")
    with _lock:
        if (not force and os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        os.makedirs(_OUT_DIR, exist_ok=True)
        cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
               src, "-o", out]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build failed:\n{proc.stderr[-2000:]}")
        return out
