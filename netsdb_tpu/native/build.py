"""Build the native runtime (g++ → shared library), cached by mtime.

Replaces the reference's SCons build of the storage engine
(``SConstruct``); one translation unit keeps it dependency-free.
"""

from __future__ import annotations

import os
import subprocess
import threading

_lock = threading.Lock()

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "pagestore.cpp")
_OUT_DIR = os.path.join(os.path.dirname(_SRC), "build")
_OUT = os.path.join(_OUT_DIR, "libpagestore.so")


class NativeBuildError(RuntimeError):
    pass


def build_library(force: bool = False) -> str:
    """Compile if missing or stale; returns the .so path."""
    with _lock:
        if (not force and os.path.exists(_OUT)
                and os.path.getmtime(_OUT) >= os.path.getmtime(_SRC)):
            return _OUT
        os.makedirs(_OUT_DIR, exist_ok=True)
        cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
               _SRC, "-o", _OUT]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build failed:\n{proc.stderr[-2000:]}")
        return _OUT
