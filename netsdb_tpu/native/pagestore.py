"""ctypes binding for the native page store (``native/pagestore.cpp``).

The reference's backend pins pages by shared-memory offset over a Unix
socket (``src/storage/headers/DataProxy.h``); here the "protocol" is a
raw pointer into the C++ arena, wrapped as a NumPy view while pinned.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_POLICIES = {"lru": 0, "mru": 1, "random": 2}

_lib = None
_lib_err: Optional[str] = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from netsdb_tpu.native.build import build_library

        path = build_library()
        lib = ctypes.CDLL(path)
    except Exception as e:  # toolchain missing → pure-Python fallback
        _lib_err = str(e)
        return None
    lib.ps_create.restype = ctypes.c_void_p
    lib.ps_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64,
                              ctypes.c_char_p, ctypes.c_int]
    lib.ps_destroy.argtypes = [ctypes.c_void_p]
    lib.ps_create_set.restype = ctypes.c_int
    lib.ps_create_set.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_int32]
    lib.ps_alloc_page.restype = ctypes.c_int64
    lib.ps_alloc_page.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_uint64]
    lib.ps_pin.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.ps_pin.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                           ctypes.POINTER(ctypes.c_uint64)]
    lib.ps_unpin.restype = ctypes.c_int
    lib.ps_unpin.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.ps_free_page.restype = ctypes.c_int
    lib.ps_free_page.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ps_flush_set.restype = ctypes.c_int
    lib.ps_flush_set.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ps_set_page_count.restype = ctypes.c_int64
    lib.ps_set_page_count.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ps_set_page_id.restype = ctypes.c_int64
    lib.ps_set_page_id.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_uint64]
    lib.ps_page_size.restype = ctypes.c_int64
    lib.ps_page_size.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ps_stats.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.c_uint64)]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


class NativePageStore:
    """Python handle on the C++ page store."""

    def __init__(self, pool_bytes: int, spill_dir: str,
                 evict_watermark: Optional[int] = None,
                 background_flush: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native page store unavailable: {_lib_err}")
        os.makedirs(spill_dir, exist_ok=True)
        watermark = evict_watermark or int(pool_bytes * 0.8)
        self._lib = lib
        self._h = lib.ps_create(pool_bytes, watermark,
                                spill_dir.encode(), int(background_flush))
        if not self._h:
            raise RuntimeError("failed to create native page store pool")

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ps_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # --- sets / pages -------------------------------------------------
    def create_set(self, set_id: int, policy: str = "lru") -> None:
        rc = self._lib.ps_create_set(self._h, set_id, _POLICIES[policy])
        if rc != 0:
            raise RuntimeError(f"create_set failed rc={rc}")

    def write_page(self, set_id: int, payload: bytes | np.ndarray) -> int:
        """Allocate a page, copy payload in, unpin dirty; returns page id."""
        buf = np.frombuffer(payload if isinstance(payload, bytes)
                            else np.ascontiguousarray(payload).tobytes(),
                            dtype=np.uint8)
        pid = self._lib.ps_alloc_page(self._h, set_id, buf.nbytes)
        if pid < 0:
            raise MemoryError(f"alloc_page failed rc={pid} "
                              f"(pool exhausted or unknown set)")
        size = ctypes.c_uint64()
        ptr = self._lib.ps_pin(self._h, pid, ctypes.byref(size))
        try:
            view = np.ctypeslib.as_array(ptr, shape=(buf.nbytes,))
            view[:] = buf
        finally:
            self._lib.ps_unpin(self._h, pid, 1)  # the write pin
        self._lib.ps_unpin(self._h, pid, 1)      # the alloc pin
        return int(pid)

    def read_page(self, page_id: int) -> bytes:
        """Pin (reloading from spill if evicted), copy out, unpin."""
        size = ctypes.c_uint64()
        ptr = self._lib.ps_pin(self._h, page_id, ctypes.byref(size))
        if not ptr:
            raise KeyError(f"unknown or unloadable page {page_id}")
        try:
            return bytes(np.ctypeslib.as_array(ptr, shape=(size.value,)))
        finally:
            self._lib.ps_unpin(self._h, page_id, 0)

    def overwrite_page(self, page_id: int,
                       payload: bytes | np.ndarray) -> None:
        """Replace one page's bytes IN PLACE (same size): pin, copy,
        unpin dirty — the update-a-column-in-its-page path."""
        buf = np.frombuffer(payload if isinstance(payload, bytes)
                            else np.ascontiguousarray(payload).tobytes(),
                            dtype=np.uint8)
        size = ctypes.c_uint64()
        ptr = self._lib.ps_pin(self._h, page_id, ctypes.byref(size))
        if not ptr:
            raise KeyError(f"unknown or unloadable page {page_id}")
        try:
            if size.value != buf.nbytes:
                raise ValueError(
                    f"overwrite_page: size change {size.value} -> "
                    f"{buf.nbytes} not allowed")
            view = np.ctypeslib.as_array(ptr, shape=(buf.nbytes,))
            view[:] = buf
        finally:
            self._lib.ps_unpin(self._h, page_id, 1)

    def free_page(self, page_id: int) -> None:
        rc = self._lib.ps_free_page(self._h, page_id)
        if rc != 0:
            raise RuntimeError(f"free_page failed rc={rc}")

    def flush_set(self, set_id: int) -> None:
        rc = self._lib.ps_flush_set(self._h, set_id)
        if rc != 0:
            raise RuntimeError(f"flush_set failed rc={rc}")

    def set_pages(self, set_id: int) -> list:
        n = self._lib.ps_set_page_count(self._h, set_id)
        if n < 0:
            raise KeyError(f"unknown set {set_id}")
        return [int(self._lib.ps_set_page_id(self._h, set_id, i))
                for i in range(n)]

    def page_size(self, page_id: int) -> int:
        """Payload bytes of one page, metadata-only (no pin/reload)."""
        n = self._lib.ps_page_size(self._h, page_id)
        if n < 0:
            raise KeyError(f"unknown page {page_id}")
        return int(n)

    def stats(self) -> dict:
        arr = (ctypes.c_uint64 * 7)()
        self._lib.ps_stats(self._h, arr)
        keys = ("hits", "misses", "evictions", "spills", "loads",
                "bytes_allocated", "bytes_in_use")
        return dict(zip(keys, [int(v) for v in arr]))
