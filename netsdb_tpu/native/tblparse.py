"""ctypes binding for the native .tbl parser (``native/tblparse.cpp``).

Columnar ingestion of TPC-H dbgen files — the C++ role of the
reference's ``tpchDataLoader.cc``, returning numpy columns instead of
per-row objects (the array form the TPU path wants). Falls back to
None when the toolchain is unavailable; callers keep the pure-Python
row parser as the portable path.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

_lib = None
_lib_err: Optional[str] = None

_TYPE_CODES = {int: 0, float: 1, str: 2}


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from netsdb_tpu.native.build import build_library

        lib = ctypes.CDLL(build_library("tblparse"))
    except Exception as e:
        _lib_err = str(e)
        return None
    lib.tp_parse.restype = ctypes.c_void_p
    lib.tp_parse.argtypes = [ctypes.c_char_p, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_int)]
    lib.tp_num_rows.restype = ctypes.c_int64
    lib.tp_num_rows.argtypes = [ctypes.c_void_p]
    lib.tp_error_msg.restype = ctypes.c_char_p
    lib.tp_error_msg.argtypes = [ctypes.c_void_p]
    lib.tp_int_col.restype = ctypes.POINTER(ctypes.c_int64)
    lib.tp_int_col.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tp_float_col.restype = ctypes.POINTER(ctypes.c_double)
    lib.tp_float_col.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tp_str_data.restype = ctypes.c_void_p
    lib.tp_str_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tp_str_offsets.restype = ctypes.POINTER(ctypes.c_int64)
    lib.tp_str_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tp_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def parse_columnar(path: str, schema: List[Tuple[str, type]]
                   ) -> Optional[Dict[str, np.ndarray]]:
    """Parse a .tbl file into {column: array} (int64 / float64 /
    object-dtype strings). Returns None when the native library is
    unavailable; raises ValueError on malformed input (same contract as
    the Python parser)."""
    lib = _load()
    if lib is None:
        return None
    types = (ctypes.c_int * len(schema))(
        *[_TYPE_CODES[t] for _, t in schema])
    h = lib.tp_parse(path.encode(), len(schema), types)
    if not h:
        raise FileNotFoundError(path)
    try:
        err = lib.tp_error_msg(h)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        n = lib.tp_num_rows(h)
        out: Dict[str, np.ndarray] = {}
        for i, (name, typ) in enumerate(schema):
            if typ is int:
                buf = np.ctypeslib.as_array(lib.tp_int_col(h, i), (n,))
                out[name] = buf.copy()
            elif typ is float:
                buf = np.ctypeslib.as_array(lib.tp_float_col(h, i), (n,))
                out[name] = buf.copy()
            else:
                offs = np.ctypeslib.as_array(lib.tp_str_offsets(h, i),
                                             (n + 1,)).copy()
                total = int(offs[-1])
                data_ptr = lib.tp_str_data(h, i)
                raw = ctypes.string_at(data_ptr, total) if total else b""
                ol = offs.tolist()
                col = np.empty(n, dtype=object)
                if raw.isascii():
                    # byte offsets == char offsets: decode once, slice
                    # (~2x faster than per-row bytes.decode)
                    blob = raw.decode()
                    col[:] = [blob[ol[j]:ol[j + 1]] for j in range(n)]
                else:
                    # multi-byte UTF-8: offsets are BYTE offsets, so
                    # slice bytes first, then decode each field
                    col[:] = [raw[ol[j]:ol[j + 1]].decode()
                              for j in range(n)]
                out[name] = col
        return out
    finally:
        lib.tp_free(h)
