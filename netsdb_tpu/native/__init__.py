from netsdb_tpu.native.pagestore import NativePageStore, native_available

__all__ = ["NativePageStore", "native_available"]
